"""1D contiguous vertex partitioning.

The reference's ownership map is ``getDev(v) = v / (numVertices / DeviceNum)``
(bfs.cu:29-32) — with a known bug: when ``V % DeviceNum != 0`` the tail
vertices map to an out-of-range device (SURVEY.md §2a row 7). Here the
partition is ``owner(v) = v // ceil(V / P)``, remainder-correct by
construction.

Vertex ids are remapped into a *padded id space* so that every chip's local
range ends with phantom slots: chip k owns real ids [k*cpk, (k+1)*cpk) and
padded ids [k*vloc, (k+1)*vloc) with vloc > cpk. Phantoms absorb padding edges
chip-locally (each chip pads with self-loops on its own phantom), and the
padded-id map is strictly monotone, so min-parent determinism is preserved
across device counts. Unlike the reference — which replicates the full CSR to
every device (initCuda2, bfs.cu:346-351) and therefore scales work but not
memory — edges are sharded by the owner of their source vertex.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_bfs.graph.csr import Graph, EDGE_PAD, _round_up


@dataclasses.dataclass(frozen=True)
class Partition1D:
    num_devices: int
    num_vertices: int  # real V
    cpk: int  # real vertices per chip (ceil(V / P))
    vloc: int  # padded local vertex count (> cpk, multiple of lane tile)
    ep_chip: int  # padded edges per chip (common max, multiple of EDGE_PAD)

    @property
    def vp(self) -> int:
        """Total padded vertex-id space."""
        return self.num_devices * self.vloc

    def owner(self, v):
        """Owning chip of real vertex v (reference getDev, bfs.cu:29-32,
        remainder-correct)."""
        return np.asarray(v) // self.cpk

    def to_padded(self, v):
        """Real vertex id -> padded id."""
        v = np.asarray(v)
        return (v // self.cpk) * self.vloc + v % self.cpk

    def from_padded(self, pid):
        """Padded id -> real vertex id (phantoms map out of range)."""
        pid = np.asarray(pid)
        return (pid // self.vloc) * self.cpk + pid % self.vloc

    def unshard(self, arr_vp: np.ndarray) -> np.ndarray:
        """[vp] padded-id-space array -> [V] real-id-space array."""
        per_chip = np.asarray(arr_vp).reshape(self.num_devices, self.vloc)
        return per_chip[:, : self.cpk].reshape(-1)[: self.num_vertices]


def partition_1d(
    graph: Graph,
    num_devices: int,
    *,
    vertex_pad: int = 1024,
    edge_pad: int = EDGE_PAD,
) -> tuple[Partition1D, np.ndarray, np.ndarray, np.ndarray]:
    """Shard a graph's edges by source owner over ``num_devices`` chips.

    Returns (partition, src_stacked, dst_stacked, rp_stacked): the stacked
    edge arrays are [P, ep_chip] int32 in *padded* vertex ids, each chip's
    slice sorted by (dst, src); padding edges run from the chip's own phantom
    source to the globally-last phantom (vp-1), preserving dst order so the
    scatter-free scan expansion works per chip. rp_stacked is the per-chip
    CSR-by-dst row pointer [P, vp+1] int32. This replaces the reference's
    full-CSR replication (bfs.cu:346-351) with true edge sharding; the
    per-destination frontier "buckets" (bfs.cu:148-150) are not materialized —
    destination routing happens in the reduce-scatter exchange.
    """
    v, p = graph.num_vertices, num_devices
    if p < 1:
        raise ValueError("num_devices must be >= 1")
    cpk = (v + p - 1) // p
    vloc = _round_up(cpk + 1, vertex_pad)
    part_src, part_dst = graph.coo
    owner = part_src.astype(np.int64) // cpk
    psrc = (part_src.astype(np.int64) // cpk) * vloc + part_src % cpk
    pdst = (part_dst.astype(np.int64) // cpk) * vloc + part_dst % cpk

    counts = np.bincount(owner, minlength=p)
    ep_chip = _round_up(int(counts.max(initial=0)) + 1, edge_pad)
    if ep_chip >= 2**31 - 1:
        raise ValueError(
            f"{ep_chip} edge slots on one chip overflow int32 row pointers; "
            "increase the device count"
        )
    part = Partition1D(
        num_devices=p, num_vertices=v, cpk=cpk, vloc=vloc, ep_chip=ep_chip
    )
    vp = part.vp

    # Order edges by (owner, dst, src); then slice per chip.
    order = np.lexsort((psrc, pdst, owner))
    owner_s = owner[order]
    psrc_s = psrc[order]
    pdst_s = pdst[order]
    starts = np.searchsorted(owner_s, np.arange(p))
    ends = np.searchsorted(owner_s, np.arange(p), side="right")
    src_stacked = np.empty((p, ep_chip), dtype=np.int32)
    dst_stacked = np.empty((p, ep_chip), dtype=np.int32)
    rp_stacked = np.empty((p, vp + 1), dtype=np.int32)
    for k in range(p):
        phantom = (k + 1) * vloc - 1  # chip k's own last (phantom) slot
        n_k = ends[k] - starts[k]
        src_stacked[k, :n_k] = psrc_s[starts[k] : ends[k]]
        dst_stacked[k, :n_k] = pdst_s[starts[k] : ends[k]]
        src_stacked[k, n_k:] = phantom
        dst_stacked[k, n_k:] = vp - 1  # last phantom: keeps dst non-decreasing
        cnt = np.bincount(dst_stacked[k].astype(np.int64), minlength=vp)
        rp_stacked[k, 0] = 0
        rp_stacked[k, 1:] = np.cumsum(cnt)
    return part, src_stacked, dst_stacked, rp_stacked


def out_csr_1d(part: Partition1D, src_stacked, dst_stacked):
    """Per-chip CSR-by-LOCAL-source view of the 1D edge shards, for the
    direction-optimizing top-down branch (frontier.sparse_topdown): chip k's
    sources all lie in its own padded range, so rows are local ids
    [0, vloc); neighbor ids stay global padded (the sparse branch scatters
    into the full [vp] contribution buffer).

    Returns (out_rp [P, vloc+1] int32, nbr [P, ep_chip] int32). Padding
    edges sit on the chip's own phantom row (vloc-1), which is never in a
    frontier."""
    p, vloc = part.num_devices, part.vloc
    ep = src_stacked.shape[1]
    out_rp = np.empty((p, vloc + 1), dtype=np.int32)
    nbr = np.empty((p, ep), dtype=np.int32)
    for k in range(p):
        src_local = src_stacked[k].astype(np.int64) - k * vloc
        order = np.argsort(src_local, kind="stable")
        nbr[k] = dst_stacked[k][order]
        cnt = np.bincount(src_local, minlength=vloc)
        out_rp[k, 0] = 0
        out_rp[k, 1:] = np.cumsum(cnt)
    return out_rp, nbr
