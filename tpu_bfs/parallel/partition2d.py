"""2D edge partitioning over an R x C device mesh.

Absent from the reference (SURVEY.md §2c: 1D vertex partitioning is its only
sharding axis) but required for the Graph500 scale-26 target (BASELINE.json).
This is the Buluc-Madduri 2D decomposition expressed TPU-natively:

- Vertices are remapped into the same padded id space as the 1D partition
  (ceil(V/P) reals + phantoms per slice, strictly monotone map); slice k is
  owned by mesh chip (i = k // C, j = k % C) — row-major.
- "Row block i" = vertices owned by mesh row i: the contiguous padded range
  [i*C*w, (i+1)*C*w).  "Column block j" = vertices owned by mesh column j:
  the *strided* union of slices {k : k % C == j}.
- Edge (u, v) lives on chip (row_of(v), col_of(u)): its frontier bit arrives
  in the column all-gather, its contribution leaves in the row
  reduce-scatter.

Per level each chip: all-gathers frontier slices over its mesh column
(receiving vp/C bits), expands local edges into a row-block contribution
(vp/R bits), and OR-reduce-scatters over its mesh row — so per-chip
communication is O(vp/R + vp/C) instead of the 1D path's O(vp).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_bfs.graph.csr import Graph, EDGE_PAD, _round_up
from tpu_bfs.parallel.partition import Partition1D


@dataclasses.dataclass(frozen=True)
class Partition2D:
    rows: int  # R
    cols: int  # C
    base: Partition1D  # flat-slice ownership (num_devices = R*C)

    @property
    def num_devices(self) -> int:
        return self.rows * self.cols

    @property
    def w(self) -> int:
        """Padded vertices per slice."""
        return self.base.vloc

    @property
    def vp(self) -> int:
        return self.base.vp

    def to_padded(self, v):
        return self.base.to_padded(v)

    def from_padded(self, pid):
        return self.base.from_padded(pid)

    def unshard(self, arr_vp):
        return self.base.unshard(arr_vp)

    def chip_of_edge(self, psrc, pdst):
        """(row, col) mesh coordinates owning padded edge (psrc, pdst)."""
        w = self.w
        return (pdst // w) // self.cols, (psrc // w) % self.cols

    def src_gather_index(self, psrc):
        """Index of padded src id within its column's all-gathered [R*w]
        frontier buffer: strided slices stacked in mesh-row order."""
        w = self.w
        return ((psrc // w) // self.cols) * w + psrc % w


def partition_2d(
    graph: Graph,
    rows: int,
    cols: int,
    *,
    vertex_pad: int = 256,
    edge_pad: int = EDGE_PAD,
):
    """Shard edges over an R x C mesh.

    Returns (part, src_gidx, dst_stacked, rp_stacked):
      - src_gidx [R, C, ep2] int32: per-chip edge sources, pre-translated into
        column-gather-local indices (see src_gather_index), sorted by dst.
      - dst_stacked [R, C, ep2] int32: global padded dst, non-decreasing per
        chip; padding edges point at the chip's row-block-final phantom.
      - rp_stacked [R, C, C*w+1] int32: per-chip CSR-by-dst row pointer over
        the chip's row block (dst made row-block-local).
    """
    v = graph.num_vertices
    p = rows * cols
    cpk = (v + p - 1) // p
    w = _round_up(cpk + 1, vertex_pad)
    base = Partition1D(
        num_devices=p, num_vertices=v, cpk=cpk, vloc=w, ep_chip=0
    )
    part = Partition2D(rows=rows, cols=cols, base=base)

    src, dst = graph.coo
    psrc = (src.astype(np.int64) // cpk) * w + src % cpk
    pdst = (dst.astype(np.int64) // cpk) * w + dst % cpk
    row = (pdst // w) // cols
    col = (psrc // w) % cols
    chip = row * cols + col

    counts = np.bincount(chip, minlength=p)
    ep2 = _round_up(int(counts.max(initial=0)) + 1, edge_pad)
    if ep2 >= 2**31 - 1:
        raise ValueError("per-chip edge slots overflow int32; use a larger mesh")

    order = np.lexsort((psrc, pdst, chip))
    chip_s = chip[order]
    psrc_s = psrc[order]
    pdst_s = pdst[order]
    starts = np.searchsorted(chip_s, np.arange(p))
    ends = np.searchsorted(chip_s, np.arange(p), side="right")

    row_block = cols * w  # dst-range size per chip
    src_gidx = np.empty((rows, cols, ep2), dtype=np.int32)
    dst_stacked = np.empty((rows, cols, ep2), dtype=np.int32)
    rp_stacked = np.empty((rows, cols, row_block + 1), dtype=np.int32)
    gather_idx = lambda ps: ((ps // w) // cols) * w + ps % w
    for i in range(rows):
        for j in range(cols):
            k = i * cols + j
            n_k = ends[k] - starts[k]
            sg = gather_idx(psrc_s[starts[k] : ends[k]])
            dl = pdst_s[starts[k] : ends[k]] - i * row_block
            src_gidx[i, j, :n_k] = sg
            dst_stacked[i, j, :n_k] = dl
            # Padding: src = slice (0, j)'s phantom (never in any frontier),
            # dst = the row block's final phantom (keeps dst non-decreasing).
            src_gidx[i, j, n_k:] = w - 1  # gather index of slice (0,j) phantom
            dst_stacked[i, j, n_k:] = row_block - 1
            cnt = np.bincount(
                dst_stacked[i, j].astype(np.int64), minlength=row_block
            )
            rp_stacked[i, j, 0] = 0
            rp_stacked[i, j, 1:] = np.cumsum(cnt)
    return part, src_gidx, dst_stacked, rp_stacked


def out_csr_2d(part: Partition2D, src_gidx, dst_stacked):
    """Per-chip CSR-by-source view of the 2D edge shards, for the
    direction-optimizing top-down branch: rows are column-gather-local
    source indices [0, R*w) (the space of the per-level column all-gather),
    neighbors are row-block-local dst ids [0, C*w) (the space of the row
    reduce-scatter's contribution buffer).

    Returns (out_rp [R, C, R*w+1] int32, nbr [R, C, ep2] int32). Padding
    edges sit on gather row w-1 — the phantom slot of mesh-row-0's slice in
    each column, never in a frontier."""
    rows, cols, w = part.rows, part.cols, part.w
    col_block = rows * w
    ep = src_gidx.shape[2]
    out_rp = np.empty((rows, cols, col_block + 1), dtype=np.int32)
    nbr = np.empty((rows, cols, ep), dtype=np.int32)
    for i in range(rows):
        for j in range(cols):
            sg = src_gidx[i, j].astype(np.int64)
            order = np.argsort(sg, kind="stable")
            nbr[i, j] = dst_stacked[i, j][order]
            cnt = np.bincount(sg, minlength=col_block)
            out_rp[i, j, 0] = 0
            out_rp[i, j, 1:] = np.cumsum(cnt)
    return out_rp, nbr
