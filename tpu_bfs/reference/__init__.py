from tpu_bfs.reference.cpu_bfs import bfs_python, bfs_scipy, bfs_golden  # noqa: F401
