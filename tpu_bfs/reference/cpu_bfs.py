"""CPU golden BFS oracles.

The reference's entire correctness story is a sequential CPU BFS run before the
GPU run and compared elementwise (bfsCPU, bfs.cu:923-945; checkOutput,
bfs.cu:374-384). We keep that pattern with two independent oracles:

- ``bfs_python``: a dependency-free queue BFS, the direct analog of bfsCPU.
  Note the reference stores parent as the *edge index* into adjacencyList
  (bfs.cu:940); we store the predecessor *vertex* id, which is
  deterministic under our min-parent rule and actually checkable (§3.4 of
  SURVEY.md: the reference's parent output is race-nondeterministic and never
  validated).
- ``bfs_scipy``: scipy.sparse.csgraph BFS at C speed, for large-graph tests
  and benchmark validation.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from tpu_bfs.graph.csr import Graph, INF_DIST, NO_PARENT


def bfs_python(g: Graph, source: int) -> tuple[np.ndarray, np.ndarray]:
    """Sequential queue BFS (analog of bfsCPU, bfs.cu:923-945).

    Returns (distance, parent): distance[v] = INF_DIST if unreached;
    parent[source] = source, parent[unreached] = -1. parent[v] is the first
    discoverer in BFS queue order — a *valid* BFS tree but not necessarily the
    device kernels' deterministic min-parent; compare parents by property
    (tpu_bfs.validate.check_parents), never elementwise.
    """
    v_count = g.num_vertices
    dist = np.full(v_count, INF_DIST, dtype=np.int32)
    parent = np.full(v_count, NO_PARENT, dtype=np.int32)
    dist[source] = 0
    parent[source] = source
    q = deque([source])
    row_ptr, col_idx = g.row_ptr, g.col_idx
    while q:
        u = q.popleft()
        du = dist[u]
        for v in col_idx[row_ptr[u] : row_ptr[u + 1]]:
            if dist[v] == INF_DIST:
                dist[v] = du + 1
                parent[v] = u
                q.append(v)
    return dist, parent


def bfs_scipy(g: Graph, source: int) -> np.ndarray:
    """Distances only, via scipy.sparse.csgraph (C implementation)."""
    import scipy.sparse.csgraph as csgraph

    d = csgraph.dijkstra(g.to_scipy(), unweighted=True, indices=source, min_only=False)
    dist = np.full(g.num_vertices, INF_DIST, dtype=np.int32)
    reached = np.isfinite(d)
    dist[reached] = d[reached].astype(np.int32)
    return dist


def bfs_golden(g: Graph, source: int, *, python_threshold: int = 200_000):
    """Pick the pure-Python oracle for small graphs, scipy for large ones."""
    if g.num_edges <= python_threshold:
        return bfs_python(g, source)[0]
    return bfs_scipy(g, source)
