"""Mesh-level fault tolerance (ISSUE 12).

The 2D-partition serving design (Buluç & Madduri, arXiv:1104.4518)
assumes a healthy mesh for every collective; this repo already lost
bench rounds r03/r04 to exactly the outage class that breaks that
assumption (utils/recovery.py records the live failure string). This
package holds the pieces that turn a mesh death from a client-visible
INTERNAL error + wedged replica into an automatic degrade-and-resume:

- :mod:`tpu_bfs.resilience.probe` — the mesh health heartbeat (a tiny
  all-reduce per replica) and the background prober that promotes a
  degraded service back onto the full mesh once it heartbeats healthy;
- :mod:`tpu_bfs.resilience.failover` — the degraded-mesh ladder (full
  mesh -> half mesh -> single chip) and the engine-kind mapping each
  rung serves with;
- :mod:`tpu_bfs.resilience.resume` — level-checkpointed query resume:
  long distributed queries snapshot their loop carry every K levels
  through the PR 4 CRC checkpoint machinery, so a mid-query mesh fault
  resumes from the last intact level on the degraded mesh instead of
  re-traversing from the source.

Detection lives with the shared classifier
(``utils/recovery.is_mesh_fault`` over ``MESH_FAULT_MARKERS``); the
serve-tier wiring (MeshFaultRequeue, the service's ``_degrade_mesh``)
lives in ``tpu_bfs/serve``; injection (``device_lost`` /
``collective_hang`` / ``backend_restart`` kinds) in ``tpu_bfs/faults``.
"""

from tpu_bfs.resilience.failover import degrade_ladder, next_mesh_rung
from tpu_bfs.resilience.probe import MeshHealthProbe, mesh_heartbeat
from tpu_bfs.resilience.resume import ResumeCache, ResumePolicy

__all__ = [
    "MeshHealthProbe",
    "ResumeCache",
    "ResumePolicy",
    "degrade_ladder",
    "mesh_heartbeat",
    "next_mesh_rung",
]
