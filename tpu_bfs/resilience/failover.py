"""The degraded-mesh failover ladder (ISSUE 12).

A mesh fault invalidates every collective the dead mesh shape runs, but
the graph tables, the registry, and the PR 9 AOT artifacts for SMALLER
meshes are all intact — so the right response is not a restart but a
rebuild one rung down: full mesh -> half mesh -> ... -> single chip.
Each rung halves the device count, so the ladder composes with the
serve tier's existing machinery unchanged:

- the width ladder re-derives from ``serve.frontend.ladder_bounds`` at
  the new device count (mesh floors shrink with the mesh);
- the circuit breaker is already keyed ``(width, devices)``, so routing
  around the dead mesh shape needs no new state — the fault feeds the
  old keys, the degraded dispatches use new ones;
- AOT artifacts are keyed on ``devices`` too (utils/aot.program_key),
  so a fleet that exported the degraded shapes ahead of time makes the
  degraded rebuild an ADOPT, not a 40 s recompile.

The single-chip rung has no exchange to partition: ``floor_config``
maps a mesh engine config onto its single-chip equivalent (the 2D
serve engine becomes the wide packed MS engine; exchange knobs drop).
"""

from __future__ import annotations


def degrade_ladder(devices: int) -> list[int]:
    """The mesh rungs a ``devices``-wide service can fail over across,
    descending: full mesh, then successive halvings, down to one chip.
    ``degrade_ladder(8) == [8, 4, 2, 1]``; a single chip has nowhere
    further to go (``[1]``)."""
    if devices < 1:
        raise ValueError(f"devices must be >= 1, got {devices}")
    rungs = []
    d = int(devices)
    while d >= 1:
        rungs.append(d)
        if d == 1:
            break
        d //= 2
    return rungs


def next_mesh_rung(devices: int) -> int | None:
    """The rung below ``devices`` (None at the single-chip floor)."""
    ladder = degrade_ladder(devices)
    return ladder[1] if len(ladder) > 1 else None


def floor_config(engine: str, exchange: str) -> tuple[str, str]:
    """``(engine, exchange)`` for a mesh engine config degraded to ONE
    chip. The 1D-partition MS engines (wide/hybrid) have single-chip
    twins under the same name; the 2D serve engine is mesh-only, so its
    single-chip rung serves through the wide packed MS engine (any
    engine over the same graph answers identically — the cross-engine
    fuzz bar). Exchange families describe MESH collectives and drop."""
    if engine == "dist2d":
        return "wide", ""
    return engine, ""
