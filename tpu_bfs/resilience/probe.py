"""Mesh health probe: a tiny all-reduce heartbeat per replica.

A mesh fault is detected where it bites (the serving fetch), but
RECOVERY needs the opposite signal — proof a mesh shape is healthy
again before traffic is routed back onto it. The heartbeat is the
smallest program that exercises the failure mode: one psum of a
replicated scalar across every device of the probed mesh, so a lost
participant, a hung collective, or a restarting backend fails the
probe exactly as it would fail a serving batch's exchange.

``mesh_heartbeat`` is the one-shot form (the degraded service's
``mesh_restore`` gates each promotion on it); :class:`MeshHealthProbe`
is the background prober a long-lived server arms
(``--mesh-probe-interval-s``) so a degraded replica climbs back to the
full mesh without an operator. Both consult the ``probe`` fault site
(tpu_bfs/faults.py), so a chaos schedule can hold a mesh "dead" past
its injected fault and prove the service stays degraded until the
probe clears.
"""

from __future__ import annotations

import threading
import time

from tpu_bfs import faults as _faults

# One compiled heartbeat per device count, reused across probes: the
# probe must stay cheap enough to run on a timer (the first call per
# count pays one tiny compile; after that it is one collective launch).
_HEARTBEATS: dict = {}  # guarded-by: _HB_LOCK
_HB_LOCK = threading.Lock()


def _heartbeat_fn(devices: int):
    with _HB_LOCK:
        fn = _HEARTBEATS.get(devices)
    if fn is not None:
        return fn

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_bfs.parallel.compat import shard_map

    avail = jax.devices()
    if devices > len(avail):
        raise ValueError(
            f"heartbeat over {devices} devices: only {len(avail)} attached"
        )
    mesh = Mesh(np.array(avail[:devices]), ("hb",))

    def local(x):
        return lax.psum(jnp.sum(x), "hb")

    inner = jax.jit(shard_map(
        local, mesh=mesh, in_specs=P("hb"), out_specs=P(), check_vma=False,
    ))
    ones = jax.device_put(
        np.ones(devices, np.int32), NamedSharding(mesh, P("hb"))
    )

    def beat():
        out = inner(ones)
        jax.block_until_ready(out)
        got = int(jnp.asarray(out))
        if got != devices:
            # A psum returning the wrong count means a participant's
            # contribution silently vanished — treat as device loss.
            raise RuntimeError(
                f"DATA_LOSS: mesh heartbeat psum returned {got}, "
                f"expected {devices} (a participant is missing)"
            )

    with _HB_LOCK:
        _HEARTBEATS[devices] = beat
    return beat


def reset_heartbeats() -> None:
    """Drop the compiled heartbeat cache (tests; and after a backend
    restart the old executables' device handles are stale anyway)."""
    with _HB_LOCK:
        _HEARTBEATS.clear()


def mesh_heartbeat(devices: int) -> float:
    """Run one all-reduce heartbeat across ``devices`` devices; returns
    the heartbeat latency in seconds. Raises whatever the collective
    raised on an unhealthy mesh (classify with
    ``utils/recovery.is_mesh_fault`` / ``is_transient_failure``)."""
    if _faults.ACTIVE is not None:
        # Chaos-harness injection site: a mesh kind scheduled at
        # "probe" makes this mesh shape report dead — holding a
        # degraded service off the full mesh until the schedule clears.
        _faults.ACTIVE.hit("probe", devices=devices)
    beat = _heartbeat_fn(devices)
    t0 = time.perf_counter()
    beat()
    return time.perf_counter() - t0


class MeshHealthProbe:
    """Background prober for a degraded service.

    Every ``interval_s`` it asks ``current()`` for the service's live
    device count; when that sits below ``target_devices`` it heartbeats
    the rungs above (widest first) and calls ``on_healthy(devices)``
    for the widest one that answers — the service's ``mesh_restore``
    hook, which rebuilds the ladder there. Probe failures are swallowed
    (the mesh is still dead; that is the expected case) but reported to
    ``log``. Daemon thread; ``stop()`` is idempotent and joins."""

    def __init__(self, target_devices: int, *, interval_s: float,
                 current, on_healthy, log=None):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.target_devices = int(target_devices)
        self.interval_s = float(interval_s)
        self._current = current
        self._on_healthy = on_healthy
        self._log = log or (lambda msg: None)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="bfs-mesh-probe", daemon=True
        )

    def start(self) -> "MeshHealthProbe":
        self._thread.start()
        return self

    def _rungs_above(self, devices: int) -> list[int]:
        from tpu_bfs.resilience.failover import degrade_ladder

        return [d for d in degrade_ladder(self.target_devices)
                if d > devices]

    def probe_once(self) -> int | None:
        """One probe pass (also the test hook): returns the device count
        promoted to, or None when nothing changed."""
        devices = self._current()
        if devices >= self.target_devices:
            return None
        for d in self._rungs_above(devices):
            try:
                latency = mesh_heartbeat(d)
            except Exception as exc:  # noqa: BLE001 — dead mesh is expected
                self._log(
                    f"mesh probe: {d}-device heartbeat failed "
                    f"({type(exc).__name__}: {str(exc)[:120]})"
                )
                continue
            self._log(
                f"mesh probe: {d}-device heartbeat healthy "
                f"({latency * 1e3:.1f} ms); promoting"
            )
            self._on_healthy(d)
            return d
        return None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.probe_once()
            except Exception as exc:  # noqa: BLE001 — the prober must survive
                self._log(f"mesh probe pass failed ({exc!r})")

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
