"""Level-checkpointed query resume (ISSUE 12).

The longest, most expensive queries run across the widest meshes —
where a device loss is most likely and most costly. Re-traversing a
deep scale-26 query from its source after a mid-query mesh fault throws
away every completed level; instead, long distributed queries snapshot
their loop carry every K levels through the PR 4 CRC checkpoint
machinery (utils/checkpoint: atomic writes, payload CRC32, quarantine
on corruption), so a fault resumes from the last intact level on the
DEGRADED mesh — checkpoints are real-vertex-id [V] arrays, portable
across mesh shapes and partition topologies by construction
(parallel.dist_bfs.VertexCheckpointMixin), which is exactly what makes
cross-mesh resume an array reshard instead of a migration.

Bounded recompute: a query that faulted at level F with snapshot
cadence K re-executes at most ``F - last_snapshot_level <= K`` levels
(proven in tests/test_mesh_chaos.py).

The cache is process-wide and keyed by GRAPH OBJECT (weakly — entries
die with the graph) then source: the degraded rebuild constructs a new
engine over the SAME registry-resident graph, so its dispatches find
the old engine's snapshots without any handoff plumbing. With a spool
directory configured (``set_default_dir`` / ``TPU_BFS_RESUME_DIR`` /
``tpu-bfs-serve --resume-dir``) every snapshot is also persisted via
``save_checkpoint`` — CRC-verified on load, corrupt files quarantined
``.corrupt`` — so a replica restart (the fleet supervisor's drain path)
can resume too, not just an in-process mesh degrade.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import weakref

#: Process-wide spool directory for on-disk snapshot persistence
#: (None = in-memory only). Read at ResumeCache construction.
_DEFAULT_DIR: str | None = os.environ.get("TPU_BFS_RESUME_DIR") or None
_DIR_LOCK = threading.Lock()


def set_default_dir(path: str | None) -> None:
    """Set the spool directory newly created caches persist through
    (the ``--resume-dir`` flag's hook); None reverts to memory-only."""
    global _DEFAULT_DIR
    with _DIR_LOCK:
        _DEFAULT_DIR = path or None


@dataclasses.dataclass(frozen=True)
class ResumePolicy:
    """When and how often a long query snapshots its loop carry.

    ``every_levels`` (K) is the snapshot cadence AND the level-loop
    chunk size: the driving engine runs the loop K levels at a time and
    snapshots at each boundary once the query qualifies as long —
    ``min_levels`` completed levels OR ``min_wall_s`` elapsed wall time
    (either threshold; 0 disables that arm). K bounds the recompute a
    mid-query fault can cost; the chunking itself re-dispatches the
    SAME compiled loop with new level bounds (no retrace)."""

    every_levels: int
    min_levels: int = 0
    min_wall_s: float = 0.0

    def __post_init__(self):
        if self.every_levels < 1:
            raise ValueError(
                f"every_levels must be >= 1, got {self.every_levels}"
            )

    def should_snapshot(self, level: int, elapsed_s: float) -> bool:
        """Snapshot at this chunk boundary? (The cadence itself is the
        chunk size; this gates only the long-query thresholds.)"""
        if self.min_levels and level >= self.min_levels:
            return True
        if self.min_wall_s and elapsed_s >= self.min_wall_s:
            return True
        return not self.min_levels and not self.min_wall_s


class ResumeCache:
    """Thread-safe source -> latest-checkpoint store for one graph.

    ``put``/``get``/``drop`` are the engine-facing API; entries are
    host ``BfsCheckpoint``s (real-id [V] arrays — mesh-portable). With
    a spool ``root`` each put also writes ``q<source>.npz`` through the
    PR 4 atomic+CRC save; ``get`` falls back to disk when memory has no
    entry (a restarted replica), and a corrupt spool file is quarantined
    by the loader and treated as absent — resume integrity must never
    be worse than starting over."""

    def __init__(self, root: str | None = None, *, log=None):
        self._log = log or (lambda msg: None)
        self.root = root
        if root:
            os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._entries: dict = {}  # guarded-by: _lock — source -> ckpt
        self.snapshots = 0  # guarded-by: _lock
        self.resumes = 0  # guarded-by: _lock

    def _path(self, source: int) -> str:
        return os.path.join(self.root, f"q{int(source)}.npz")

    def put(self, source: int, ckpt) -> None:
        with self._lock:
            self._entries[int(source)] = ckpt
            self.snapshots += 1
        if self.root:
            from tpu_bfs.utils.checkpoint import save_checkpoint

            try:
                save_checkpoint(self._path(source), ckpt)
            except OSError as exc:
                # Spool persistence is an optimization over the
                # in-memory copy; a full disk must not fail the query.
                self._log(f"resume spool write failed ({exc!r}); "
                          f"keeping the in-memory snapshot only")

    def get(self, source: int):
        """The latest snapshot for ``source`` (None when there is none
        or the only copy on disk failed its CRC)."""
        with self._lock:
            ckpt = self._entries.get(int(source))
        if ckpt is not None or not self.root:
            return ckpt
        from tpu_bfs.utils.checkpoint import (
            CorruptCheckpointError,
            load_checkpoint,
        )

        path = self._path(source)
        if not os.path.exists(path):
            return None
        try:
            ckpt = load_checkpoint(path)
        except CorruptCheckpointError as exc:
            # Already quarantined (.corrupt) by the loader: resume from
            # level 0 rather than from poisoned state.
            self._log(f"resume spool entry corrupt ({exc}); starting over")
            return None
        except (OSError, ValueError) as exc:
            self._log(f"resume spool read failed ({exc!r}); starting over")
            return None
        with self._lock:
            self._entries[int(source)] = ckpt
        return ckpt

    def mark_resumed(self, source: int) -> None:
        """Account one mid-query resume (the engine calls this when a
        dispatch starts from a cached level instead of the source)."""
        from tpu_bfs.utils.recovery import COUNTERS

        with self._lock:
            self.resumes += 1
        COUNTERS.bump("query_resumes")

    def drop(self, source: int) -> None:
        """Forget ``source``'s snapshot (its query completed)."""
        with self._lock:
            self._entries.pop(int(source), None)
        if self.root:
            try:
                os.unlink(self._path(source))
            except OSError:
                pass

    def counts(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "snapshots": self.snapshots,
                "resumes": self.resumes,
            }


# One cache per live graph object: the degraded rebuild's engine is
# constructed over the same registry-resident graph, so it finds the
# failed engine's snapshots here with no explicit handoff. Keyed by
# id() with a weakref finalizer (Graph holds ndarrays and is not
# hashable; the identity check below makes id reuse after gc harmless).
_GRAPH_CACHES: dict = {}  # guarded-by: _CACHE_LOCK — id -> (ref, cache)
# RLock: the weakref finalizer below may fire from a gc triggered while
# this thread already holds the lock inside cache_for_graph.
_CACHE_LOCK = threading.RLock()


def cache_for_graph(graph, *, log=None) -> ResumeCache:
    key = id(graph)
    with _CACHE_LOCK:
        ent = _GRAPH_CACHES.get(key)
        if ent is not None and ent[0]() is graph:
            return ent[1]
        with _DIR_LOCK:
            root = _DEFAULT_DIR
        cache = ResumeCache(root, log=log)

        def _drop(_ref, _key=key):
            with _CACHE_LOCK:
                _GRAPH_CACHES.pop(_key, None)

        _GRAPH_CACHES[key] = (weakref.ref(graph, _drop), cache)
        return cache
