"""Lane-batching BFS query server (ISSUE 2).

The packed engines' lane axis is a request-batching axis: one device
dispatch answers up to ``lanes`` independent sources (msbfs_packed.py /
msbfs_wide.py — the MS-BFS batching idea, same motivation as the batched
frontier processing in the distributed-memory BFS literature, PAPERS.md).
This package turns that into a long-lived query service instead of the
one-shot CLI's fresh-process-per-query flow:

- ``registry``  — load graphs once, build-and-warm engines keyed by
  (graph, engine, lanes, pull_gate, devices, exchange config,
  mesh_shape) with an LRU bound, warm-up hitting the persistent XLA
  cache (utils/compile_cache.py); with devices > 1 the resident rungs
  are the DISTRIBUTED engines spanning the mesh (ISSUE 11 — the 1D
  packed MS engines and the 2D edge partition behind ``dist2d``);
- ``scheduler`` — bounded admission queue coalescing pending single-source
  queries into one packed batch per dispatch (linger knob trades latency
  for batch fill; per-query deadlines; shed-on-overload);
- ``executor``  — batch dispatch through the engines' async
  dispatch/fetch halves, with transient-failure retry and OOM lane-count
  degrade on BOTH halves (classifier shared with utils/recovery.py), a
  dispatch watchdog (a hung device fetch is classified transient instead
  of wedging the executor), and a per-width circuit breaker over
  deterministic failures (routing goes around an open rung; half-open
  probe on a timer);
- ``frontend``  — the in-process ``BfsService`` API (adaptive width
  ladder: each batch routes to the narrowest warmed width that fits;
  pipelined extraction: a worker pulls batch N's results while batch N+1
  dispatches) and the stdin/stdout JSONL protocol behind the
  ``tpu-bfs-serve`` entry point;
- ``metrics``   — /statsz-style serve counters (QPS, p50/p99 latency,
  fill ratio vs dispatched width, per-width routing histogram, pad
  waste, extraction time, queue depth, retries, sheds, watchdog trips,
  breaker state, requeue-budget sheds).

Lifecycle (robustness issue): the JSONL server drains gracefully on
SIGTERM/SIGINT (admission stops, in-flight batches flush, queued queries
resolve SHUTDOWN, final statsz emitted), and the whole failure surface is
exercised by the deterministic chaos harness (tpu_bfs/faults.py,
``--faults`` / TPU_BFS_FAULTS) — see README "Failure model".

Mesh fault tolerance (ISSUE 12, tpu_bfs/resilience): a mesh-death error
on a multi-chip batch (``utils/recovery.is_mesh_fault``) runs the
degraded-mesh failover ladder — the service rebuilds its rungs on a
halved mesh, re-admits the batch's queries, and (``resume_levels=K``,
dist2d) resumes them from their level checkpoints; the health probe
promotes back onto the full mesh once it heartbeats healthy, and
scripts/fleet_supervisor.py supervises N replicas of the whole thing.
"""

from tpu_bfs.serve.executor import CircuitBreaker  # noqa: F401
from tpu_bfs.serve.frontend import BfsService  # noqa: F401
from tpu_bfs.serve.metrics import ServeMetrics  # noqa: F401
from tpu_bfs.serve.registry import EngineRegistry, EngineSpec  # noqa: F401
from tpu_bfs.serve.scheduler import (  # noqa: F401
    STATUS_ERROR,
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHUTDOWN,
    AdmissionQueue,
    PendingQuery,
    QueryResult,
)
