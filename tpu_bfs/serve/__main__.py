"""``python -m tpu_bfs.serve`` — the JSONL query server (frontend.py)."""

import sys

from tpu_bfs.serve.frontend import main

if __name__ == "__main__":
    sys.exit(main())
