"""Byte-budgeted answer cache for the serve hot path (ISSUE 18).

Production traffic at scale is Zipfian: the same hub sources are asked
constantly, yet before this tier every served query paid a full
traversal. The :class:`AnswerCache` resolves the popular head without
touching the scheduler at all:

- **bounded LRU, byte-budgeted**: entries are whole terminal payloads
  (distance row, levels, reached, extras) keyed
  ``(graph_key, graph_generation, cache_generation, kind, source,
  k, target, want_distances)``; inserting past ``max_bytes`` evicts
  from the cold end. The graph-generation field is constant today and
  exists so ROADMAP item 2's dynamic-graph generation flip invalidates
  every entry by key, not by scan.
- **CRC32 discipline** (the PR 4 checkpoint rule, applied in memory):
  each entry's payload blob is checksummed at ``put`` and re-verified
  at every hit; a mismatch — storage rot, or the ``corrupt_cache_entry``
  chaos kind flipping a byte at the ``cache_lookup`` fault site —
  degrades the hit to a miss and evicts the entry. The ``stale_cache``
  kind mutates a CRC-VALID hit instead, which only the sampled shadow
  audit can catch (tpu_bfs/integrity): a confirmed stale entry
  quarantines the cache GENERATION, not a serving rung.
- **population at resolve time**: the extraction worker calls ``put``
  after a batch resolves (serve/frontend._finish) — the dispatch path
  never writes the cache, so a cache stall cannot delay a dispatch.

Single-flight collapsing of identical in-flight queries lives with the
admission machinery (serve/scheduler.InflightIndex) — it dedupes
traversals whether or not this cache is armed; the cache then keeps the
answer around after the flight lands.

Thread-safe: client threads hit ``get`` concurrently with the
extraction worker's ``put`` and the audit thread's
``quarantine_generation``; one lock guards the store.
"""

from __future__ import annotations

import threading
import zlib
from collections import OrderedDict

import numpy as np

from tpu_bfs import faults
from tpu_bfs import obs as _obs

#: Default payload budget: ~64 MB holds ~4000 scale-12 distance rows —
#: far past the Zipfian head a serving replica actually sees.
DEFAULT_MAX_BYTES = 64 << 20

#: Extras keys this tier STAMPS onto responses (provenance + bound
#: metadata). The shadow auditor strips them before comparing a cached
#: answer against its replay (integrity/shadow.compare_payloads), and
#: the fuzz arms ignore them when pinning cache-on == cache-off.
PROVENANCE_EXTRAS = frozenset(
    ("cache_hit", "landmark", "exact", "bound_lo", "bound_hi")
)


class _Entry:
    __slots__ = ("key", "blob", "levels", "reached", "extras", "crc",
                 "nbytes", "width", "devices")

    def __init__(self, key, blob, levels, reached, extras, crc, nbytes,
                 width, devices):
        self.key = key
        self.blob = blob  # distance row bytes, or None (metadata kinds)
        self.levels = levels
        self.reached = reached
        self.extras = extras
        self.crc = crc
        self.nbytes = nbytes
        self.width = width
        self.devices = devices


def _payload_crc(blob: bytes | None, levels, reached, extras) -> int:
    """CRC32 over the full terminal payload — the distance blob plus a
    canonical rendering of the metadata fields, so a mutation of ANY
    served field (not just the distance row) trips verification."""
    crc = zlib.crc32(blob) if blob is not None else zlib.crc32(b"\x00")
    meta = repr((levels, reached,
                 sorted(extras.items()) if extras else None))
    return zlib.crc32(meta.encode(), crc)


class AnswerCache:
    """The serve tier's resolved-answer store. See the module docstring
    for the contract; :class:`~tpu_bfs.serve.metrics.ServeMetrics` hooks
    (when provided) keep hits/misses/evictions/bytes on statsz."""

    def __init__(self, *, graph_key: str = "", graph_generation: int = 0,
                 max_bytes: int = DEFAULT_MAX_BYTES, metrics=None,
                 log=None):
        if max_bytes < 1:
            raise ValueError(f"cache byte budget must be >= 1, got "
                             f"{max_bytes}")
        self.graph_key = graph_key
        self.graph_generation = int(graph_generation)
        self.max_bytes = int(max_bytes)
        self.metrics = metrics
        self.log = log or (lambda *_a, **_k: None)
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        self._generation = 0  # guarded-by: _lock
        self._quarantines = 0  # guarded-by: _lock

    # --- keys -------------------------------------------------------------

    def _key(self, kind, source, k, target, want_distances,
             generation) -> tuple:
        return (self.graph_key, self.graph_generation, generation,
                kind, int(source),
                None if k is None else int(k),
                None if target is None else int(target),
                bool(want_distances))

    def generation(self) -> int:
        with self._lock:
            return self._generation

    def set_graph_generation(self, generation: int) -> None:
        """Dynamic-graph flip (ISSUE 19): adopt the new served graph
        version. Entries keyed under the old generation become
        unreachable INSTANTLY (invalidation by key, never by scan —
        exactly what the key field was reserved for); their bytes drain
        off the cold end of the LRU as fresh traffic inserts."""
        with self._lock:
            self.graph_generation = int(generation)

    # --- store ------------------------------------------------------------

    def put(self, *, kind: str, source: int, k=None, target=None,
            want_distances: bool = True, distances=None, levels=None,
            reached=None, extras=None, width=None, devices=None) -> None:
        """Insert one resolved payload (extraction-worker path). Extras
        are stored without this tier's own provenance keys, so a
        re-served hit stamps fresh provenance instead of echoing stale
        ones."""
        if extras:
            extras = {k2: v for k2, v in extras.items()
                      if k2 not in PROVENANCE_EXTRAS}
        blob = None
        if distances is not None:
            blob = np.ascontiguousarray(distances, dtype=np.int32).tobytes()
        nbytes = (len(blob) if blob else 64) + 64
        if nbytes > self.max_bytes:
            return  # one oversized row must not wipe the whole cache
        crc = _payload_crc(blob, levels, reached, extras)
        evicted = 0
        with self._lock:
            key = self._key(kind, source, k, target, want_distances,
                            self._generation)
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = _Entry(
                key, blob, levels, reached, extras, crc, nbytes,
                width, devices,
            )
            self._bytes += nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, cold = self._entries.popitem(last=False)
                self._bytes -= cold.nbytes
                evicted += 1
            nbytes_now = self._bytes
        if self.metrics is not None:
            if evicted:
                self.metrics.record_cache_eviction(evicted)
            self.metrics.set_cache_bytes(nbytes_now)

    def get(self, *, kind: str, source: int, k=None, target=None,
            want_distances: bool = True):
        """One lookup on the submit path. Returns a payload dict
        (``distances``/``levels``/``reached``/``extras``/``width``/
        ``devices``/``generation``) or None on miss — including the
        degraded-to-miss path where CRC verification caught a corrupt
        entry (the entry is evicted and the miss is counted)."""
        with self._lock:
            gen = self._generation
            key = self._key(kind, source, k, target, want_distances, gen)
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
                blob = e.blob
        if e is None:
            if self.metrics is not None:
                self.metrics.record_cache_miss()
            return None
        if faults.ACTIVE is not None:
            # Chaos: corrupt_cache_entry rots the STORED blob so the
            # verification below fires exactly as on real storage rot.
            if blob is not None:
                blob, fired = faults.maybe_corrupt_cache_blob(
                    blob, query_kind=kind, source=source,
                )
                if fired:
                    with self._lock:
                        e.blob = blob
        if _payload_crc(e.blob, e.levels, e.reached, e.extras) != e.crc:
            self._evict_corrupt(key, e)
            return None
        dist = None
        if e.blob is not None:
            dist = np.frombuffer(e.blob, dtype=np.int32)
        extras = dict(e.extras) if e.extras else None
        reached = e.reached
        if faults.ACTIVE is not None:
            # Chaos: stale_cache serves a CRC-valid wrong answer — the
            # shadow audit's generation-quarantine red-before-green.
            dist, extras, reached, _fired = faults.maybe_stale_cache(
                dist, extras, reached, query_kind=kind, source=source,
            )
        return {
            "distances": dist,
            "levels": e.levels,
            "reached": reached,
            "extras": extras,
            "width": e.width,
            "devices": e.devices,
            "generation": gen,
        }

    def _evict_corrupt(self, key, e) -> None:
        with self._lock:
            if self._entries.get(key) is e:
                self._entries.pop(key)
                self._bytes -= e.nbytes
            nbytes_now = self._bytes
        self.log(f"answer cache: CRC mismatch on {key!r} — entry "
                 f"evicted, hit degraded to a miss")
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event("cache_corrupt_entry", cat="serve.cache",
                      kind=key[3], source=key[4])
        if self.metrics is not None:
            self.metrics.record_cache_eviction()
            self.metrics.record_cache_miss()
            self.metrics.set_cache_bytes(nbytes_now)

    # --- quarantine -------------------------------------------------------

    def quarantine_generation(self, *, detail: str = "") -> int:
        """A confirmed stale/corrupt CACHED answer poisons trust in the
        whole resident generation, not one entry and not a serving rung:
        bump the generation (every old key becomes unreachable) and drop
        the store. Returns the new generation."""
        with self._lock:
            self._generation += 1
            self._quarantines += 1
            self._entries.clear()
            self._bytes = 0
            gen = self._generation
        self.log(f"answer cache QUARANTINED -> generation {gen}"
                 + (f" ({detail})" if detail else ""))
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event("cache_quarantine", cat="serve.cache",
                      generation=gen, detail=detail)
            rec.flight_dump("cache_quarantine")
        if self.metrics is not None:
            self.metrics.record_cache_quarantine()
            self.metrics.set_cache_bytes(0)
        return gen

    # --- introspection ----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "generation": self._generation,
                "quarantines": self._quarantines,
            }

    def config_summary(self) -> dict:
        """The statsz config echo (mirrors IntegrityTier's)."""
        out = self.stats()
        out["graph_generation"] = self.graph_generation
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
