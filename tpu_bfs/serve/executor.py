"""Batch execution with failure classification, retry, and OOM degrade.

The serving dispatch path reuses the one transient/deterministic
classifier the whole repo shares (utils/recovery.py): transient infra
errors re-dispatch the SAME batch with capped backoff (the queries are
already coalesced; re-enqueueing them would just re-form the same
batch), OOM hands the queries back to the service for re-admission at a
narrower lane count (floor_lanes halving — the degrade ladder), and
everything else resolves the batch's queries with explicit error
results. Unlike bench.py's retry ladder there is no wall-clock budget:
the server is the long-lived process the budget envelope exists to
protect elsewhere.

The execution is split into PIPELINE HALVES (ISSUE 3): ``dispatch_batch``
launches the device level loop through the engine's async ``dispatch``
entry and returns a :class:`PendingBatch` immediately; ``finish_batch``
blocks on the result and extracts/resolves. The split lets the service
hand completed batches to an extraction worker and keep dispatching —
and because JAX surfaces async-dispatch failures (OOM included) at the
blocking fetch, the SAME classifier runs on both halves: a transient
fetch failure re-dispatches the identical padded batch, an OOM raises
:class:`OomRequeue` from whichever half saw it, and every admitted query
still resolves exactly once.
"""

from __future__ import annotations

import time

import numpy as np

from tpu_bfs.serve.scheduler import STATUS_ERROR, STATUS_OK, QueryResult
from tpu_bfs.utils.recovery import (
    COUNTERS,
    is_oom_failure,
    is_transient_failure,
)


def pad_batch(sources: np.ndarray, lanes: int) -> tuple[np.ndarray, int]:
    """Pad a partial batch to exactly ``lanes`` sources so every dispatch
    reuses ONE compiled shape per ladder width (a variable-length batch
    would retrace the level loop per distinct size). Pad lanes repeat the
    first real source — a valid vertex by construction — and are masked
    out on extract by never being read (lanes [n:) belong to no query).
    With the width ladder the residual waste is bounded: routing already
    picked the narrowest resident width >= n, and what's left shows up in
    the ``padded_lanes_total`` counter."""
    n = len(sources)
    if n > lanes:
        raise ValueError(f"batch of {n} exceeds {lanes} lanes")
    if n == lanes:
        return np.asarray(sources, dtype=np.int64), n
    out = np.empty(lanes, dtype=np.int64)
    out[:n] = sources
    out[n:] = sources[0]
    return out, n


class OomRequeue(Exception):
    """Internal signal: the batch OOM'd; its queries ride along for the
    service to degrade the lane count and re-admit."""

    def __init__(self, queries, cause: BaseException):
        super().__init__(str(cause))
        self.queries = queries
        self.cause = cause


class PendingBatch:
    """One dispatched-but-unresolved batch crossing the pipeline handoff.

    Carries everything either half needs: the engine, the admitted
    queries (for exactly-once resolution), the padded source array (so a
    transient fetch failure can re-dispatch the identical batch), the
    async handle, and the retry attempt counter — shared across both
    halves so the retry budget cannot double through the handoff."""

    __slots__ = ("engine", "queries", "n", "padded", "handle", "attempt",
                 "lanes")

    def __init__(self, engine, queries, n: int, padded: np.ndarray):
        self.engine = engine
        self.queries = list(queries)
        self.n = n
        self.padded = padded
        self.handle = None
        self.attempt = 0
        # Recorded at dispatch: the OOM handler clears ``engine`` to drop
        # the device-table reference before a narrower rebuild, but the
        # service still needs the width the failure happened at.
        self.lanes = engine.lanes


class _Ready:
    """Degenerate handle for engines exposing only the blocking ``run``
    protocol (test fakes): the whole run happens at dispatch time."""

    __slots__ = ("res",)

    def __init__(self, res):
        self.res = res


class BatchExecutor:
    """Runs coalesced batches through an engine's dispatch/fetch halves."""

    def __init__(self, metrics, *, max_retries: int = 2,
                 backoff_s: float = 0.05, backoff_cap_s: float = 2.0,
                 log=None, sleep=time.sleep):
        self.metrics = metrics
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self._log = log or (lambda msg: None)
        self._sleep = sleep

    # --- pipeline halves --------------------------------------------------

    def dispatch_batch(self, engine, queries) -> PendingBatch | None:
        """Pad and launch ``queries`` (<= engine.lanes of them) as one
        batch WITHOUT blocking on the result. Returns the pending handoff
        (resolve via :meth:`finish_batch`), or None when the batch already
        resolved with deterministic errors. Raises :class:`OomRequeue` on
        a dispatch-time OOM — the only outcome that leaves the queries
        unresolved, because re-admission at a narrower width is the
        service's call, not the executor's."""
        sources = np.asarray([q.source for q in queries], dtype=np.int64)
        padded, n = pad_batch(sources, engine.lanes)
        pending = PendingBatch(engine, queries, n, padded)
        while True:
            try:
                pending.handle = self._dispatch(engine, padded)
                return pending
            except Exception as exc:  # noqa: BLE001 — gated by the classifier
                if not self._classify_failure(pending, exc):
                    return None

    def finish_batch(self, pending: PendingBatch) -> None:
        """Block on a dispatched batch and resolve every query exactly
        once. Transient fetch failures re-dispatch the same padded batch
        (the handle is dead once its fetch raised); OOM raises
        :class:`OomRequeue` exactly as the dispatch half does."""
        engine = pending.engine
        while True:
            try:
                if pending.handle is None:  # re-dispatch after a retry
                    pending.handle = self._dispatch(engine, pending.padded)
                res = self._fetch(engine, pending.handle)
                break
            except Exception as exc:  # noqa: BLE001 — gated by the classifier
                pending.handle = None
                if not self._classify_failure(pending, exc):
                    return
        # The result now owns whatever device state extraction needs; drop
        # the handle's copy so the batch's loop outputs free as soon as
        # the result does.
        pending.handle = None
        self._resolve_ok(pending, res)

    def run_batch(self, engine, queries) -> None:
        """The unpipelined path: dispatch immediately finished."""
        pending = self.dispatch_batch(engine, queries)
        if pending is not None:
            self.finish_batch(pending)

    # --- internals --------------------------------------------------------

    @staticmethod
    def _dispatch(engine, padded):
        dispatch = getattr(engine, "dispatch", None)
        if dispatch is not None:
            return dispatch(padded)
        return _Ready(engine.run(padded, time_it=False))

    @staticmethod
    def _fetch(engine, handle):
        if isinstance(handle, _Ready):
            return handle.res
        return engine.fetch(handle)

    def _classify_failure(self, pending: PendingBatch, exc) -> bool:
        """The one classifier both halves share. True = retry the batch;
        False = resolved as deterministic errors; OOM raises OomRequeue."""
        if is_oom_failure(exc):
            raise OomRequeue(list(pending.queries), exc) from exc
        if is_transient_failure(exc) and pending.attempt < self.max_retries:
            pending.attempt += 1
            wait = min(self.backoff_s * pending.attempt, self.backoff_cap_s)
            self.metrics.record_retry()
            COUNTERS.bump("transient_retries")
            self._log(
                f"transient failure serving a {pending.n}-query batch "
                f"(attempt {pending.attempt}/{self.max_retries}): "
                f"{type(exc).__name__}: {str(exc)[:200]} — "
                f"retrying in {wait:.2f}s"
            )
            self._sleep(wait)
            return True
        err = f"{type(exc).__name__}: {str(exc)[:300]}"
        self._log(f"batch failed deterministically: {err}")
        for q in pending.queries:
            q.resolve_status(STATUS_ERROR, error=err)
        self.metrics.record_errors(pending.n)
        return False

    def _resolve_ok(self, pending: PendingBatch, res) -> None:
        from tpu_bfs.graph.csr import INF_DIST

        engine, queries, n = pending.engine, pending.queries, pending.n
        width = engine.lanes
        # The on-device ecc summary is only worth its kernel dispatch when
        # some query skips the distance decode; all-want_distances batches
        # derive levels from the rows they pull anyway.
        ecc = (
            getattr(res, "ecc", None)
            if any(not getattr(q, "want_distances", True) for q in queries)
            else None
        )
        t_x0 = time.monotonic()
        latencies = []
        for i, q in enumerate(queries):
            want = getattr(q, "want_distances", True)
            d = None
            if want or ecc is None:
                # The one per-lane device->host distance pull. Metadata-only
                # queries skip it entirely when the engine reduced the
                # summaries on device (ecc — every packed engine does).
                d = res.distances_int32(i)
            if ecc is not None:
                levels = int(ecc[i])
            else:
                finite = d[d != INF_DIST]
                levels = int(finite.max()) if finite.size else 0
            # Stamp at RESOLVE time, per query: extraction cost is real
            # client-visible latency (the old shared pre-extraction stamp
            # hid it, and hid the pipelining win with it).
            latency_ms = (time.monotonic() - q.t_submit) * 1e3
            q.resolve(QueryResult(
                id=q.id,
                source=q.source,
                status=STATUS_OK,
                distances=d if want else None,
                levels=levels,
                reached=int(res.reached[i]),
                latency_ms=latency_ms,
                batch_lanes=n,
                dispatched_lanes=width,
            ))
            latencies.append(latency_ms)
        extract_ms = (time.monotonic() - t_x0) * 1e3
        self.metrics.record_batch(n, width, latencies, extract_ms=extract_ms)
