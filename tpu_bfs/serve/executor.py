"""Batch execution with failure classification, retry, and OOM degrade.

The serving dispatch path reuses the one transient/deterministic
classifier the whole repo shares (utils/recovery.py): transient infra
errors re-dispatch the SAME batch with capped backoff (the queries are
already coalesced; re-enqueueing them would just re-form the same
batch), OOM hands the queries back to the service for re-admission at a
narrower lane count (floor_lanes halving — the degrade ladder), and
everything else resolves the batch's queries with explicit error
results. Unlike bench.py's retry ladder there is no wall-clock budget:
the server is the long-lived process the budget envelope exists to
protect elsewhere.

The execution is split into PIPELINE HALVES (ISSUE 3): ``dispatch_batch``
launches the device level loop through the engine's async ``dispatch``
entry and returns a :class:`PendingBatch` immediately; ``finish_batch``
blocks on the result and extracts/resolves. The split lets the service
hand completed batches to an extraction worker and keep dispatching —
and because JAX surfaces async-dispatch failures (OOM included) at the
blocking fetch, the SAME classifier runs on both halves: a transient
fetch failure re-dispatches the identical padded batch, an OOM raises
:class:`OomRequeue` from whichever half saw it, and every admitted query
still resolves exactly once.
"""

from __future__ import annotations

import itertools
import threading
import time

import numpy as np

from tpu_bfs import faults as _faults
from tpu_bfs import obs as _obs
from tpu_bfs.serve.scheduler import (
    STATUS_ERROR,
    STATUS_EXPIRED,
    STATUS_OK,
    QueryResult,
)
from tpu_bfs.utils.recovery import (
    COUNTERS,
    is_mesh_fault,
    is_oom_failure,
    is_transient_failure,
)


def pad_batch(sources: np.ndarray, lanes: int) -> tuple[np.ndarray, int]:
    """Pad a partial batch to exactly ``lanes`` sources so every dispatch
    reuses ONE compiled shape per ladder width (a variable-length batch
    would retrace the level loop per distinct size). Pad lanes repeat the
    first real source — a valid vertex by construction — and are masked
    out on extract by never being read (lanes [n:) belong to no query).
    With the width ladder the residual waste is bounded: routing already
    picked the narrowest resident width >= n, and what's left shows up in
    the ``padded_lanes_total`` counter."""
    n = len(sources)
    if n > lanes:
        raise ValueError(f"batch of {n} exceeds {lanes} lanes")
    if n == lanes:
        return np.asarray(sources, dtype=np.int64), n
    out = np.empty(lanes, dtype=np.int64)
    out[:n] = sources
    out[n:] = sources[0]
    return out, n


def engine_devices(engine) -> int:
    """The device count an engine's batches span — 1 for the single-chip
    engines, the mesh size for the distributed ones. The breaker and the
    degrade bookkeeping key on (width, devices): a single-chip rung
    tripping must not blackhole the same width on the mesh path (and
    vice versa), because the two are DIFFERENT compiled programs over
    different device sets (ISSUE 11). One definition shared with the
    fault sites' ``devices`` context (faults.mesh_devices) so the
    rank-qualifier semantics and the breaker keys cannot drift."""
    return _faults.mesh_devices(engine)


def breaker_key(width: int, devices: int, kind: str = "bfs") -> tuple:
    """The partition-aware breaker/degrade key: ``(width, devices)``,
    extended with the query kind when non-default (ISSUE 14) — a broken
    sssp rung must not blackhole the same width's bfs engine (different
    compiled programs), while default-kind keys keep the PR 10/11 tuple
    shape existing pins and dashboards read."""
    base = (int(width), int(devices))
    return base if kind == "bfs" else base + (kind,)


class CircuitBreaker:
    """Per-key (dispatch width x device count) circuit breaker over
    DETERMINISTIC batch failures.

    A rung whose every dispatch fails deterministically (wedged device
    state, a compiler bug tripped by one shape) would otherwise burn its
    full retry ladder on every batch routed to it, forever. The breaker
    OPENS after ``threshold`` consecutive deterministic failures at a
    key: the service's router then skips that rung (queries route to the
    next wider one). After ``cooldown_s`` it HALF-OPENS — one probe batch
    is admitted; success closes the breaker, failure re-opens it for
    another cooldown. OOMs never count here (the width-degrade ladder
    already evicts and routes around those); transient failures never
    count (the retry ladder owns them).

    Thread-safe; open transitions bump ``RecoveryCounters.breaker_opens``
    and are visible in statsz (``breaker_open`` / ``breaker_opens``)."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, *, threshold: int = 3, cooldown_s: float = 30.0,
                 now=time.monotonic, log=None):
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1, got {threshold}")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._now = now
        self._log = log or (lambda msg: None)
        self._lock = threading.Lock()
        # key -> [state, consecutive_fails, opened_at]
        self._state: dict = {}  # guarded-by: _lock
        self.opens = 0  # guarded-by: _lock

    def allow(self, key) -> bool:
        """May a batch be routed to ``key`` right now? Open keys refuse
        until the cooldown elapses, then admit exactly one probe."""
        with self._lock:
            st = self._state.get(key)
            if st is None or st[0] == self.CLOSED:
                return True
            # OPEN past the cooldown admits one probe (half-open); a
            # HALF_OPEN whose probe never reported back (lost outside the
            # executor, e.g. a failed engine build) re-admits one more
            # probe per cooldown period — a lost probe must not block the
            # rung forever.
            if self._now() - st[2] >= self.cooldown_s:
                st[0] = self.HALF_OPEN
                st[2] = self._now()
                self._log(f"circuit breaker half-open for width {key}: "
                          f"admitting one probe batch")
                return True
            return False  # open, or half-open with the probe in flight

    def record_success(self, key) -> None:
        with self._lock:
            st = self._state.pop(key, None)
            if st is not None and st[0] != self.CLOSED:
                self._log(f"circuit breaker closed for width {key} "
                          f"(probe batch succeeded)")

    def record_failure(self, key) -> bool:
        """Count one deterministic failure; True when the breaker OPENED
        (first crossing of the threshold, or a failed half-open probe)."""
        with self._lock:
            st = self._state.setdefault(key, [self.CLOSED, 0, 0.0])
            st[1] += 1
            opened = (
                st[0] == self.HALF_OPEN
                or (st[0] == self.CLOSED and st[1] >= self.threshold)
            )
            if opened:
                st[0] = self.OPEN
                st[2] = self._now()
                self.opens += 1
        if opened:
            COUNTERS.bump("breaker_opens")
            self._log(
                f"circuit breaker OPEN for width {key} after {st[1]} "
                f"consecutive deterministic failures (cooldown "
                f"{self.cooldown_s:.1f}s)"
            )
        return opened

    def trip(self, key) -> None:
        """Force-open ``key`` immediately — the integrity tier's
        corruption quarantine (ISSUE 15): a rung whose AUDITED answer
        was provably corrupt must stop taking traffic now, not after
        ``threshold`` more batches of wrong answers. Half-opens on the
        ordinary cooldown timer like any open breaker (the evicted
        rung's rebuild gets its probe batch)."""
        with self._lock:
            st = self._state.setdefault(key, [self.CLOSED, 0, 0.0])
            st[0] = self.OPEN
            st[1] = max(st[1], self.threshold)
            st[2] = self._now()
            self.opens += 1
        COUNTERS.bump("breaker_opens")
        self._log(f"circuit breaker FORCED OPEN for {key} (corruption "
                  f"quarantine; cooldown {self.cooldown_s:.1f}s)")

    def open_keys(self) -> list:
        """Keys currently open/half-open (for statsz)."""
        with self._lock:
            return sorted(
                k for k, st in self._state.items() if st[0] != self.CLOSED
            )


# Batch ordinals are assigned unconditionally (one integer increment):
# the obs layer needs a stable correlation id, and tests that spy on the
# disabled path count obs-layer CALLS, not plain counters.
_BATCH_SEQ = itertools.count(1)


class BatchRequeue(Exception):
    """Base of the internal batch-outcome signals that leave queries
    UNRESOLVED and ride up to the service: re-admission policy (which
    width, which mesh) is the service's call, not the executor's. Both
    pipeline halves close their open spans on any subclass."""

    def __init__(self, queries, cause: BaseException):
        super().__init__(str(cause))
        self.queries = queries
        self.cause = cause


class OomRequeue(BatchRequeue):
    """The batch OOM'd; its queries ride along for the service to
    degrade the lane count and re-admit."""


class MeshFaultRequeue(BatchRequeue):
    """The batch's MESH died under it (device loss / hung collective /
    backend restart — utils/recovery.is_mesh_fault): retrying on the
    same mesh shape would re-dispatch into the same dead collective, so
    the queries ride up for the service to rebuild the ladder one mesh
    rung down (ISSUE 12's failover ladder) and re-admit. ``devices``
    records the mesh span the fault hit."""

    def __init__(self, queries, cause: BaseException, devices: int):
        super().__init__(queries, cause)
        self.devices = devices


class PendingBatch:
    """One dispatched-but-unresolved batch crossing the pipeline handoff.

    Carries everything either half needs: the engine, the admitted
    queries (for exactly-once resolution), the padded source array (so a
    transient fetch failure can re-dispatch the identical batch), the
    async handle, and the retry attempt counter — shared across both
    halves so the retry budget cannot double through the handoff."""

    __slots__ = ("engine", "queries", "n", "padded", "handle", "attempt",
                 "lanes", "bid", "devices", "t_dispatch", "device_ms",
                 "wire_bytes", "kind", "params", "generation",
                 "overlay_epoch")

    def __init__(self, engine, queries, n: int, padded: np.ndarray,
                 kind: str = "bfs", params: dict | None = None):
        self.engine = engine
        self.queries = list(queries)
        self.n = n
        self.padded = padded
        # The batch's query kind + its batch-uniform dispatch kwargs
        # (ISSUE 14: khop's k, p2p's padded targets) — carried so a
        # transient re-dispatch on either pipeline half replays the
        # identical call.
        self.kind = kind
        self.params = params or {}
        self.handle = None
        self.attempt = 0
        # Recorded at dispatch: the OOM handler clears ``engine`` to drop
        # the device-table reference before a narrower rebuild, but the
        # service still needs the width the failure happened at. In
        # LADDER units: an adapter whose batch capacity differs from its
        # registry width (p2p counts pairs) publishes ``ladder_lanes``
        # so the breaker keys and the OOM-degrade walk stay on the
        # service's width grid.
        self.lanes = getattr(engine, "ladder_lanes", engine.lanes)
        # Mesh span of this batch's engine — half of the partition-aware
        # breaker key, recorded here for the same clears-engine reason.
        self.devices = engine_devices(engine)
        # Dispatch stamp -> fetch-return duration: the batch's device
        # occupancy, the denominator of the per-query GTEPS record.
        self.t_dispatch: float | None = None
        self.device_ms: float | None = None
        # Modeled off-chip bytes the batch's traversal moved (mesh
        # engines; None on single-chip — there is no wire).
        self.wire_bytes: float | None = None
        # Process-wide batch ordinal: the span-correlation id every obs
        # event of this batch (and its queries) carries.
        self.bid = next(_BATCH_SEQ)
        # Graph generation this batch was ADMITTED under (ISSUE 19):
        # stamped by the scheduler inside the flip lock at dispatch, so
        # the stamp always names the generation of the engine tables the
        # batch actually traversed — the staleness auditor's ground
        # truth. Static services leave it 0.
        self.generation = 0
        # Overlay install epoch at the same dispatch point: bumps on
        # table events the generation number cannot see (restage heals,
        # compactions), so the shadow auditor can tell "replayable
        # against the live tables" from "superseded install".
        self.overlay_epoch = 0


class _Ready:
    """Degenerate handle for engines exposing only the blocking ``run``
    protocol (test fakes): the whole run happens at dispatch time."""

    __slots__ = ("res",)

    def __init__(self, res):
        self.res = res


class BatchExecutor:
    """Runs coalesced batches through an engine's dispatch/fetch halves."""

    def __init__(self, metrics, *, max_retries: int = 2,
                 backoff_s: float = 0.05, backoff_cap_s: float = 2.0,
                 log=None, sleep=time.sleep, watchdog_s: float = 0.0,
                 breaker: CircuitBreaker | None = None):
        self.metrics = metrics
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self._log = log or (lambda msg: None)
        self._sleep = sleep
        # Dispatch watchdog: > 0 bounds how long the blocking fetch half
        # may run before being CLASSIFIED AS TRANSIENT (the existing
        # retry/rebuild path fires instead of the executor hanging
        # forever on a wedged device). 0 keeps the plain inline fetch.
        self.watchdog_s = watchdog_s
        self.breaker = breaker
        # Every watchdog trip abandons a daemon thread still blocked in
        # engine.fetch, pinning that batch's device handle until the
        # fetch eventually returns. On a permanently wedged device those
        # would accumulate forever; past this cap new watched fetches are
        # REFUSED with a deterministic error (feeding the breaker, which
        # then routes around the rung) instead of abandoning more state.
        self.max_abandoned = 8
        self._abandoned = 0  # guarded-by: _abandon_lock
        self._abandon_lock = threading.Lock()

    # --- pipeline halves --------------------------------------------------

    def dispatch_batch(self, engine, queries) -> PendingBatch | None:
        """Pad and launch ``queries`` (<= engine.lanes of them) as one
        batch WITHOUT blocking on the result. Returns the pending handoff
        (resolve via :meth:`finish_batch`), or None when the batch already
        resolved with deterministic errors. Raises :class:`OomRequeue` on
        a dispatch-time OOM — the only outcome that leaves the queries
        unresolved, because re-admission at a narrower width is the
        service's call, not the executor's."""
        # Deadline re-check at DISPATCH time: batch-forming already
        # expired queued queries, but a query can reach this point again
        # long after that check — an OOM requeue, a breaker reroute, or
        # a mesh-degrade re-admission — and burning chip time on an
        # answer its client stopped waiting for helps nobody.
        now = time.monotonic()
        live = []
        expired = 0
        for q in queries:
            if q.expired(now):
                if q.resolve_status(
                    STATUS_EXPIRED,
                    error="deadline expired before dispatch "
                          "(after requeue/reroute)",
                ):
                    expired += 1
            else:
                live.append(q)
        if expired:
            self.metrics.record_expired(expired)
        if not live:
            return None
        queries = live
        sources = np.asarray([q.source for q in queries], dtype=np.int64)
        padded, n = pad_batch(sources, engine.lanes)
        # Per-kind dispatch kwargs (ISSUE 14): the scheduler only
        # coalesces same-batch-key queries, so the first query's kind
        # and parameters speak for the whole batch. p2p's targets pad
        # exactly like the sources (pad pairs clone pair 0).
        kind = getattr(queries[0], "kind", "bfs")
        from tpu_bfs.workloads import batch_params

        params = batch_params(queries)
        if "targets" in params:
            params["targets"], _ = pad_batch(
                params["targets"], engine.lanes
            )
        pending = PendingBatch(engine, queries, n, padded, kind, params)
        rec = _obs.ACTIVE
        if rec is not None:
            # The batch span opens at dispatch and closes when every
            # query resolved (finish) or the batch failed; every query's
            # own span learns its batch id here. Latest wins: a query
            # requeued out of an OOM'd batch must close naming the batch
            # that actually served it, not the aborted one (the aborted
            # batch's own events still list the query id).
            for q in pending.queries:
                if hasattr(q, "obs_batch"):
                    q.obs_batch = pending.bid
            mesh_kw = (
                {"devices": pending.devices} if pending.devices > 1 else {}
            )
            rec.begin("batch", f"b{pending.bid}",  # span-outlives: finish_batch/_extract/_classify_failure close it
                      cat="serve.batch",
                      batch=pending.bid, n=n, width=pending.lanes,
                      queries=[q.id for q in pending.queries], **mesh_kw)
            rec.begin("dispatch", f"b{pending.bid}", cat="serve.batch",
                      batch=pending.bid, width=pending.lanes, **mesh_kw)
        while True:
            try:
                if _faults.ACTIVE is not None:
                    # Chaos-harness injection site: engine-agnostic (the
                    # _packed_common dispatch/fetch sites cover real
                    # engines; this one also covers test doubles).
                    _faults.ACTIVE.hit("serve_batch", lanes=pending.lanes,
                                       n=pending.n)
                pending.t_dispatch = time.monotonic()
                pending.handle = self._dispatch(engine, padded,
                                                pending.params)
                if rec is not None:
                    rec.end("dispatch", f"b{pending.bid}", cat="serve.batch",
                            batch=pending.bid, attempt=pending.attempt)
                return pending
            except Exception as exc:  # noqa: BLE001 — gated by the classifier
                try:
                    retry = self._classify_failure(pending, exc)
                except BatchRequeue as brq:
                    # The OOM/mesh-fault rides up to the service's
                    # requeue ladder; the open dispatch span must not
                    # dangle in the trace (the classifier already ended
                    # the batch span).
                    if rec is not None:
                        rec.end("dispatch", f"b{pending.bid}",
                                cat="serve.batch", batch=pending.bid,
                                **({"oom": True}
                                   if isinstance(brq, OomRequeue)
                                   else {"mesh_fault": True}))
                    raise
                if not retry:
                    if rec is not None:
                        rec.end("dispatch", f"b{pending.bid}",
                                cat="serve.batch", batch=pending.bid,
                                failed=True)
                        rec.end("batch", f"b{pending.bid}", cat="serve.batch",
                                batch=pending.bid, failed=True)
                    return None

    def finish_batch(self, pending: PendingBatch) -> None:
        """Block on a dispatched batch and resolve every query exactly
        once. Transient fetch failures re-dispatch the same padded batch
        (the handle is dead once its fetch raised); OOM raises
        :class:`OomRequeue` exactly as the dispatch half does."""
        engine = pending.engine
        rec = _obs.ACTIVE
        if rec is not None:
            rec.begin("fetch", f"b{pending.bid}", cat="serve.batch",
                      batch=pending.bid, n=pending.n)
        while True:
            try:
                if pending.handle is None:  # re-dispatch after a retry
                    pending.t_dispatch = time.monotonic()
                    pending.handle = self._dispatch(
                        engine, pending.padded, pending.params
                    )
                res = self._fetch_watched(engine, pending)
                # The batch's device occupancy — the per-query GTEPS
                # denominator. Under pipelining, dispatch time includes
                # the wait behind the previous in-flight batch (one
                # device stream), so the window is clamped to start no
                # earlier than the previous batch's fetch-return on this
                # engine: an approximation of the true compute window
                # (slightly late on the start side), but it no longer
                # double-counts the predecessor's whole runtime.
                t_done = time.monotonic()
                if pending.t_dispatch is not None:
                    start = pending.t_dispatch
                    prev_done = engine.__dict__.get("_serve_prev_fetch_done")
                    if prev_done is not None and prev_done > start:
                        start = prev_done
                    pending.device_ms = (t_done - start) * 1e3
                engine.__dict__["_serve_prev_fetch_done"] = t_done
                # Modeled exchange bytes: the READY-only reader — fetch
                # of batch N must not block on (or wait for) batch N+1's
                # still-running loop. See completed_exchange_record for
                # the bounded adjacent-batch attribution caveat.
                taker = getattr(engine, "completed_exchange_record", None)
                wb = (
                    taker()[1] if taker is not None
                    else getattr(engine, "last_exchange_bytes", None)
                )
                pending.wire_bytes = None if wb is None else float(wb)
                break
            except Exception as exc:  # noqa: BLE001 — gated by the classifier
                pending.handle = None
                try:
                    retry = self._classify_failure(pending, exc)
                except BatchRequeue as brq:
                    # Same discipline as the dispatch half: close the
                    # open fetch span before the OOM/mesh-fault rides up.
                    if rec is not None:
                        rec.end("fetch", f"b{pending.bid}", cat="serve.batch",
                                batch=pending.bid,
                                **({"oom": True}
                                   if isinstance(brq, OomRequeue)
                                   else {"mesh_fault": True}))
                    raise
                if not retry:
                    if rec is not None:
                        rec.end("fetch", f"b{pending.bid}", cat="serve.batch",
                                batch=pending.bid, failed=True)
                        rec.end("batch", f"b{pending.bid}", cat="serve.batch",
                                batch=pending.bid, failed=True)
                    return
        if rec is not None:
            rec.end("fetch", f"b{pending.bid}", cat="serve.batch",
                    batch=pending.bid, attempt=pending.attempt)
        # The result now owns whatever device state extraction needs; drop
        # the handle's copy so the batch's loop outputs free as soon as
        # the result does.
        pending.handle = None
        self._resolve_ok(pending, res)

    def run_batch(self, engine, queries) -> None:
        """The unpipelined path: dispatch immediately finished."""
        pending = self.dispatch_batch(engine, queries)
        if pending is not None:
            self.finish_batch(pending)

    # --- internals --------------------------------------------------------

    @staticmethod
    def _dispatch(engine, padded, params=None):
        dispatch = getattr(engine, "dispatch", None)
        if dispatch is not None:
            return dispatch(padded, **params) if params else dispatch(padded)
        if params:
            return _Ready(engine.run(padded, time_it=False, **params))
        return _Ready(engine.run(padded, time_it=False))

    @staticmethod
    def _fetch(engine, handle):
        if isinstance(handle, _Ready):
            return handle.res
        return engine.fetch(handle)

    def _fetch_watched(self, engine, pending: PendingBatch):
        """The blocking fetch, under the dispatch watchdog when armed.

        A device computation that exceeds ``watchdog_s`` is CLASSIFIED AS
        TRANSIENT (a DEADLINE_EXCEEDED RuntimeError the shared classifier
        retries), so a wedged device fires the existing re-dispatch path
        instead of hanging the executor forever. The abandoned fetch runs
        on a daemon thread; if it eventually completes, its result is
        discarded — the batch's queries resolve exactly once through
        whichever attempt the retry ladder lands.

        Deliberately one thread PER watched fetch, not a persistent
        worker: after a trip the abandoned fetch may block its thread
        indefinitely, and the retry's fetch must proceed concurrently —
        a single long-lived worker would serialize behind exactly the
        hang the watchdog exists to route around. Thread spawn cost is
        noise next to a device level-loop fetch."""
        if self.watchdog_s <= 0:
            return self._fetch(engine, pending.handle)
        with self._abandon_lock:
            # Captured under the lock: the refusal message reads the count
            # too, and a trip on another thread must not race the read
            # (the lock lint in tpu_bfs/analysis pins the discipline).
            abandoned = self._abandoned
        if abandoned >= self.max_abandoned:
            # Deterministic (no transient marker): resolves the batch's
            # queries with errors and feeds the breaker, instead of
            # abandoning yet another fetch on a wedged device.
            raise RuntimeError(
                f"dispatch watchdog: {abandoned} abandoned fetches "
                f"still running (cap {self.max_abandoned}); refusing to "
                f"watch another fetch on this engine"
            )
        box: list = []
        done = threading.Event()
        state = {"abandoned": False}

        def work(handle=pending.handle):
            try:
                box.append(("ok", self._fetch(engine, handle)))
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                box.append(("err", exc))
            finally:
                # done + the abandoned-count handoff commute under one
                # lock: either the watcher sees done in time, or it marks
                # the thread abandoned and this finally pays it back.
                with self._abandon_lock:
                    if state["abandoned"]:
                        self._abandoned -= 1
                    done.set()

        threading.Thread(
            target=work, name="bfs-serve-fetch", daemon=True
        ).start()
        if not done.wait(self.watchdog_s):
            tripped = False
            with self._abandon_lock:
                if not done.is_set():
                    state["abandoned"] = True
                    self._abandoned += 1
                    tripped = True
            if tripped:
                COUNTERS.bump("watchdog_trips")
                self.metrics.record_watchdog_trip()
                rec = _obs.ACTIVE
                if rec is not None:
                    # Flight-recorder trigger: the trip is exactly the
                    # incident class the ring buffer exists to replay.
                    rec.event("watchdog_trip", cat="serve.batch",
                              batch=pending.bid, n=pending.n,
                              watchdog_s=self.watchdog_s,
                              queries=[q.id for q in pending.queries])
                    rec.flight_dump("watchdog_trip")
                raise RuntimeError(
                    f"DEADLINE_EXCEEDED: dispatch watchdog: a "
                    f"{pending.n}-query batch's device fetch is still "
                    f"running after {self.watchdog_s:.1f}s — classifying "
                    f"as transient"
                )
        kind, val = box[0]
        if kind == "err":
            raise val
        return val

    def _classify_failure(self, pending: PendingBatch, exc) -> bool:
        """The one classifier both halves share. True = retry the batch;
        False = resolved as deterministic errors; OOM raises OomRequeue."""
        rec = _obs.ACTIVE
        if is_oom_failure(exc):
            if rec is not None:
                rec.event("batch_oom", cat="serve.batch", batch=pending.bid,
                          width=pending.lanes,
                          queries=[q.id for q in pending.queries])
                rec.end("batch", f"b{pending.bid}", cat="serve.batch",
                        batch=pending.bid, oom=True)
            raise OomRequeue(list(pending.queries), exc) from exc
        if pending.devices > 1 and is_mesh_fault(exc):
            # A mesh-death marker on a MESH-spanning batch (ISSUE 12):
            # the whole mesh shape is suspect, so an in-place retry
            # would re-dispatch into the same dead collective. Feed the
            # (width, devices) breaker — routing stops offering the dead
            # mesh shape while its probe half-opens — and hand the
            # queries up for the degraded-mesh rebuild. Single-chip
            # batches with the same markers fall through to the plain
            # transient retry below (nothing to degrade).
            err = f"{type(exc).__name__}: {str(exc)[:200]}"
            COUNTERS.bump("mesh_faults")
            self.metrics.record_mesh_fault()
            self._log(
                f"MESH FAULT on a {pending.devices}-device batch "
                f"(width {pending.lanes}): {err} — degrading the mesh"
            )
            if self.breaker is not None:
                self.breaker.record_failure(
                    breaker_key(pending.lanes, pending.devices,
                                pending.kind)
                )
            if rec is not None:
                # Flight-recorder trigger (every mesh-fault firing):
                # the run-up to a slice death is exactly what the ring
                # buffer exists to replay.
                rec.event("mesh_fault", cat="serve.batch",
                          batch=pending.bid, width=pending.lanes,
                          devices=pending.devices, error=err,
                          queries=[q.id for q in pending.queries])
                rec.end("batch", f"b{pending.bid}", cat="serve.batch",
                        batch=pending.bid, mesh_fault=True)
                rec.flight_dump("mesh_fault")
            raise MeshFaultRequeue(
                list(pending.queries), exc, pending.devices
            ) from exc
        if is_transient_failure(exc) and pending.attempt < self.max_retries:
            pending.attempt += 1
            wait = min(self.backoff_s * pending.attempt, self.backoff_cap_s)
            self.metrics.record_retry()
            COUNTERS.bump("transient_retries")
            if rec is not None:
                rec.event("retry", cat="serve.batch", batch=pending.bid,
                          attempt=pending.attempt,
                          error=f"{type(exc).__name__}: {str(exc)[:120]}")
            self._log(
                f"transient failure serving a {pending.n}-query batch "
                f"(attempt {pending.attempt}/{self.max_retries}): "
                f"{type(exc).__name__}: {str(exc)[:200]} — "
                f"retrying in {wait:.2f}s"
            )
            self._sleep(wait)
            return True
        err = f"{type(exc).__name__}: {str(exc)[:300]}"
        self._log(f"batch failed deterministically: {err}")
        if rec is not None:
            rec.event("batch_error", cat="serve.batch", batch=pending.bid,
                      width=pending.lanes, error=err,
                      queries=[q.id for q in pending.queries])
        if self.breaker is not None:
            # Deterministic failures (exhausted transients included) feed
            # the per-(width, devices) breaker so routing stops paying
            # this rung's full retry ladder per batch once it is provably
            # broken — without blackholing the same width on a different
            # mesh span.
            opened = self.breaker.record_failure(
                breaker_key(pending.lanes, pending.devices, pending.kind)
            )
            if opened and rec is not None:
                # Flight-recorder trigger: a rung going provably dark is
                # an incident worth a replayable artifact.
                rec.event("breaker_open", cat="serve.batch",
                          width=pending.lanes, batch=pending.bid)
                rec.flight_dump("breaker_open")
        for q in pending.queries:
            q.resolve_status(STATUS_ERROR, error=err)
        self.metrics.record_errors(pending.n)
        return False

    def _resolve_ok(self, pending: PendingBatch, res) -> None:
        if self.breaker is not None:
            self.breaker.record_success(
                breaker_key(pending.lanes, pending.devices,
                            pending.kind)
            )
        rec = _obs.ACTIVE
        if rec is not None:
            rec.begin("extract", f"b{pending.bid}",  # span-outlives: _extract ends it; the except arm below covers the failure path
                      cat="serve.batch",
                      batch=pending.bid, n=pending.n)
        try:
            self._extract(pending, res, rec)
        except Exception:
            # An extraction failure propagates to the service's catch-all
            # (which flight-dumps it); the open extract/batch spans must
            # not dangle in the very trace written for that incident.
            if rec is not None:
                rec.end("extract", f"b{pending.bid}", cat="serve.batch",
                        batch=pending.bid, failed=True)
                rec.end("batch", f"b{pending.bid}", cat="serve.batch",
                        batch=pending.bid, failed=True)
            raise

    def _extract(self, pending: PendingBatch, res, rec) -> None:
        from tpu_bfs.graph.csr import INF_DIST

        engine, queries, n = pending.engine, pending.queries, pending.n
        # Ladder units (ladder_lanes where the adapter's capacity
        # differs): the width responses/metrics report must match the
        # routing histogram's rungs.
        width = pending.lanes
        # The on-device ecc summary is only worth its kernel dispatch when
        # some query skips the distance decode; all-want_distances batches
        # derive levels from the rows they pull anyway.
        ecc = (
            getattr(res, "ecc", None)
            if any(not getattr(q, "want_distances", True) for q in queries)
            else None
        )
        # Per-query traversal record (ISSUE 11): the engines' on-device
        # per-lane edge counts + the batch's device occupancy give each
        # query its GTEPS under the batch time share; mesh engines add
        # their modeled wire bytes, split evenly over the real queries.
        edges_arr = getattr(res, "edges_traversed", None)
        # Kind-specific response fields (ISSUE 14): workload results
        # expose per-query extras (p2p's path, cc's component record,
        # khop's k); the base engines' results have none.
        extras_fn = getattr(res, "extras", None)
        wire_share = (
            pending.wire_bytes / n
            if pending.wire_bytes is not None and n else None
        )
        t_x0 = time.monotonic()
        latencies = []
        for i, q in enumerate(queries):
            want = getattr(q, "want_distances", True)
            d = None
            if want or ecc is None:
                # The one per-lane device->host distance pull. Metadata-only
                # queries skip it entirely when the engine reduced the
                # summaries on device (ecc — every packed engine does).
                d = res.distances_int32(i)
            if ecc is not None:
                levels = int(ecc[i])
            else:
                finite = d[d != INF_DIST]
                levels = int(finite.max()) if finite.size else 0
            extras_i = extras_fn(i) if extras_fn is not None else None
            reached_i = int(res.reached[i])
            if _faults.ACTIVE is not None:
                # Chaos hook (ISSUE 15): corrupt_result rules flip one
                # bit of THIS query's just-extracted answer — the
                # client-visible corruption every integrity detector
                # must catch (red-before-green for the audit tier).
                d, extras_i, reached_i, _fired = _faults.maybe_corrupt_result(
                    d, extras_i, reached_i, lanes=width, batch=pending.bid,
                )
            # Stamp at RESOLVE time, per query: extraction cost is real
            # client-visible latency (the old shared pre-extraction stamp
            # hid it, and hid the pipelining win with it).
            latency_ms = (time.monotonic() - q.t_submit) * 1e3
            q.resolve(QueryResult(
                id=q.id,
                source=q.source,
                status=STATUS_OK,
                kind=pending.kind,
                extras=extras_i,
                distances=d if want else None,
                levels=levels,
                reached=reached_i,
                latency_ms=latency_ms,
                batch_lanes=n,
                dispatched_lanes=width,
                devices=pending.devices,
                edges=(
                    int(edges_arr[i]) if edges_arr is not None else None
                ),
                device_ms=pending.device_ms,
                wire_bytes=wire_share,
            ))
            latencies.append(latency_ms)
        extract_ms = (time.monotonic() - t_x0) * 1e3
        if rec is not None:
            rec.end("extract", f"b{pending.bid}", cat="serve.batch",
                    batch=pending.bid, extract_ms=round(extract_ms, 3))
            rec.end("batch", f"b{pending.bid}", cat="serve.batch",
                    batch=pending.bid, n=n, width=width)
        self.metrics.record_batch(n, width, latencies, extract_ms=extract_ms)
