"""Batch execution with failure classification, retry, and OOM degrade.

The serving dispatch path reuses the one transient/deterministic
classifier the whole repo shares (utils/recovery.py): transient infra
errors re-dispatch the SAME batch with capped backoff (the queries are
already coalesced; re-enqueueing them would just re-form the same
batch), OOM hands the queries back to the service for re-admission at a
narrower lane count (floor_lanes halving — the degrade ladder), and
everything else resolves the batch's queries with explicit error
results. Unlike bench.py's retry ladder there is no wall-clock budget:
the server is the long-lived process the budget envelope exists to
protect elsewhere.
"""

from __future__ import annotations

import time

import numpy as np

from tpu_bfs.serve.scheduler import STATUS_ERROR, STATUS_OK, QueryResult
from tpu_bfs.utils.recovery import (
    COUNTERS,
    is_oom_failure,
    is_transient_failure,
)


def pad_batch(sources: np.ndarray, lanes: int) -> tuple[np.ndarray, int]:
    """Pad a partial batch to exactly ``lanes`` sources so every dispatch
    reuses ONE compiled shape (a variable-length batch would retrace the
    level loop per distinct size). Pad lanes repeat the first real source
    — a valid vertex by construction — and are masked out on extract by
    never being read (lanes [n:) belong to no query)."""
    n = len(sources)
    if n > lanes:
        raise ValueError(f"batch of {n} exceeds {lanes} lanes")
    if n == lanes:
        return np.asarray(sources, dtype=np.int64), n
    out = np.empty(lanes, dtype=np.int64)
    out[:n] = sources
    out[n:] = sources[0]
    return out, n


class OomRequeue(Exception):
    """Internal signal: the batch OOM'd; its queries ride along for the
    service to degrade the lane count and re-admit."""

    def __init__(self, queries, cause: BaseException):
        super().__init__(str(cause))
        self.queries = queries
        self.cause = cause


class BatchExecutor:
    """Runs coalesced batches through an engine's ``run`` protocol."""

    def __init__(self, metrics, *, max_retries: int = 2,
                 backoff_s: float = 0.05, backoff_cap_s: float = 2.0,
                 log=None, sleep=time.sleep):
        self.metrics = metrics
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self._log = log or (lambda msg: None)
        self._sleep = sleep

    def run_batch(self, engine, queries) -> None:
        """Dispatch ``queries`` (<= engine.lanes of them) as one padded
        batch and resolve every query exactly once. Raises
        :class:`OomRequeue` when the dispatch OOM'd — the only outcome
        that leaves the queries unresolved, because re-admission (at a
        narrower width) is the service's call, not the executor's."""
        sources = np.asarray([q.source for q in queries], dtype=np.int64)
        padded, n = pad_batch(sources, engine.lanes)
        attempt = 0
        while True:
            try:
                res = engine.run(padded, time_it=False)
                break
            except Exception as exc:  # noqa: BLE001 — gated by the classifier
                if is_oom_failure(exc):
                    raise OomRequeue(list(queries), exc) from exc
                if is_transient_failure(exc) and attempt < self.max_retries:
                    attempt += 1
                    wait = min(self.backoff_s * attempt, self.backoff_cap_s)
                    self.metrics.record_retry()
                    COUNTERS.bump("transient_retries")
                    self._log(
                        f"transient failure serving a {n}-query batch "
                        f"(attempt {attempt}/{self.max_retries}): "
                        f"{type(exc).__name__}: {str(exc)[:200]} — "
                        f"retrying in {wait:.2f}s"
                    )
                    self._sleep(wait)
                    continue
                err = f"{type(exc).__name__}: {str(exc)[:300]}"
                self._log(f"batch failed deterministically: {err}")
                for q in queries:
                    q.resolve_status(STATUS_ERROR, error=err)
                self.metrics.record_errors(n)
                return
        self._resolve_ok(engine, res, queries, n)

    def _resolve_ok(self, engine, res, queries, n: int) -> None:
        from tpu_bfs.graph.csr import INF_DIST

        t_done = time.monotonic()
        latencies = []
        for i, q in enumerate(queries):
            d = res.distances_int32(i)
            finite = d[d != INF_DIST]
            latency_ms = (t_done - q.t_submit) * 1e3
            q.resolve(QueryResult(
                id=q.id,
                source=q.source,
                status=STATUS_OK,
                distances=d,
                levels=int(finite.max()) if finite.size else 0,
                reached=int(res.reached[i]),
                latency_ms=latency_ms,
                batch_lanes=n,
            ))
            latencies.append(latency_ms)
        self.metrics.record_batch(n, engine.lanes, latencies)
