"""The serving front-end: in-process ``BfsService`` + stdin/stdout JSONL.

``BfsService`` is the API tests and the bench drive; the JSONL loop
(``tpu-bfs-serve`` / ``python -m tpu_bfs.serve``) is the same service
behind a line protocol:

    request   {"id": 7, "source": 12345}
              (+ "deadline_ms", + "want_distances": false)
    response  {"id": 7, "source": 12345, "status": "ok", "levels": 6,
               "reached": 104857, "latency_ms": 18.4, "batch_lanes": 31,
               "dispatched_lanes": 32, "distances_npy": "<base64 .npy>"}

With ``--mutations`` (ISSUE 19) the wire also takes edge updates:

    request   {"id": 9, "op": "mutate", "add": [[1, 2], [3, 4, 7]],
               "remove": [[5, 6]]}
    response  {"id": 9, "op": "mutate", "ok": true, "generation": 3,
               "flip_ms": 1.8, "overlay_rows": 2, "compacted": false}

Non-ok responses carry ``status`` in {rejected, deadline_exceeded,
error, shutdown} plus ``error``. Responses are emitted as queries
complete (batch order, not arrival order); ``id`` is the correlation
key. stdout carries ONLY protocol lines; logs and the periodic statsz
line go to stderr.

Adaptive dispatch (ISSUE 3): the service holds a small geometric WIDTH
LADDER of warmed engines (default rungs lanes/16, lanes/4, lanes — e.g.
32/128/512) and routes each coalesced batch to the narrowest rung that
fits, so a 3-query batch stops paying 512 lanes of compute; and result
extraction runs on a dedicated worker (PIPELINED, the engines'
dispatch/fetch split), so the scheduler thread is already forming and
dispatching batch N+1 while batch N's distances are still being pulled.
The scheduler thread owns all BFS dispatch as before; the extraction
worker's device work is limited to result readback of already-completed
batches.
"""

from __future__ import annotations

import argparse
import base64
import dataclasses
import io
import json
import os
import queue as _queue
import signal
import sys
import threading
import time

import numpy as np

from tpu_bfs import faults as _faults
from tpu_bfs import obs as _obs
from tpu_bfs.resilience.failover import floor_config, next_mesh_rung
from tpu_bfs.serve.executor import (
    BatchExecutor,
    CircuitBreaker,
    MeshFaultRequeue,
    OomRequeue,
)
from tpu_bfs.serve.answercache import AnswerCache
from tpu_bfs.serve.metrics import ServeMetrics
from tpu_bfs.serve.registry import DEFAULT_PLANES, EngineRegistry, EngineSpec
from tpu_bfs.serve.scheduler import (
    STATUS_ERROR,
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHUTDOWN,
    AdmissionQueue,
    InflightIndex,
    PendingQuery,
    QueryResult,
)
from tpu_bfs.utils.recovery import (
    COUNTERS,
    is_mesh_fault,
    is_oom_failure,
    is_transient_failure,
)
from tpu_bfs.workloads import (
    KINDS,
    METADATA_ONLY_KINDS,
    kind_unsupported_reason,
    supported_kinds,
)

MIN_LANES = 32
# Auto ladder spacing: each rung 4x the previous (32/128/512 at the
# default 512 max). Factor 4 keeps the rung count (and the HBM cost of
# resident engines) low while bounding pad waste per batch below the
# dispatched width's 3/4 — the routing histogram in /statsz shows where
# traffic actually lands.
LADDER_FACTOR = 4


def ladder_bounds(lanes: int, *, devices: int = 1,
                  engine: str = "wide") -> tuple[int, int]:
    """``(floor, quantum)`` of the serving widths for this engine/mesh.

    The single-chip defaults (floor 32, quantum 32) are sized for one
    chip's lane budget; a mesh ladder must scale both (ISSUE 11):

    - the HYBRID engines' dense MXU kernel takes whole 4096-lane steps
      (single-chip and distributed alike), so both floor and quantum are
      4096 — an auto ladder stops warming widths the engine cannot even
      build;
    - other mesh engines keep the 32-lane quantum but raise the floor to
      ``32 * devices``: a whole mesh dispatching a 32-lane batch pays P
      chips' collectives for work one chip holds in a single rung — no
      partition benefits from rungs below that line, while the widest
      rungs (the ones a mesh can actually hold) stay.
    """
    from tpu_bfs.serve.registry import HYBRID_LANE_QUANTUM

    if engine == "hybrid":
        return HYBRID_LANE_QUANTUM, HYBRID_LANE_QUANTUM
    if devices > 1:
        return min(lanes, MIN_LANES * devices), MIN_LANES
    return MIN_LANES, MIN_LANES


def build_width_ladder(lanes: int, ladder="auto", *, devices: int = 1,
                       engine: str = "wide") -> list:
    """The service's resident widths, ascending, topped by ``lanes``.

    ``"auto"`` walks down from ``lanes`` by :data:`LADDER_FACTOR` to the
    engine/mesh floor (:func:`ladder_bounds` — 32 on one chip, scaled on
    a mesh); ``"off"``/None serves one fixed width (the pre-ladder
    behavior, and the A/B baseline); an explicit sequence gives the rungs
    directly (each a multiple of the width quantum in [floor, lanes])."""
    floor, quantum = ladder_bounds(lanes, devices=devices, engine=engine)
    if ladder in (None, "off"):
        return [lanes]
    if isinstance(ladder, str) and ladder != "auto":
        ladder = [int(tok) for tok in ladder.replace(",", " ").split()]
    if ladder == "auto":
        rungs = {lanes}
        w = lanes
        while w > floor:
            w = max(floor, (w // LADDER_FACTOR) // quantum * quantum)
            rungs.add(w)
        return sorted(rungs)
    rungs = sorted({int(w) for w in ladder} | {lanes})
    for w in rungs:
        if w % quantum or not (floor <= w <= lanes):
            raise ValueError(
                f"ladder width {w} must be a multiple of {quantum} in "
                f"[{floor}, {lanes}]"
            )
    return rungs


@dataclasses.dataclass(frozen=True)
class MeshServeConfig:
    """The service's CURRENT engine/mesh configuration — everything
    ``_spec`` stamps into a registry key. One immutable object swapped
    atomically (the ``_closed``/``_draining`` lock-free-flag idiom):
    the mesh failover ladder (ISSUE 12) replaces it wholesale when a
    mesh fault degrades the service to a smaller device count, and the
    health probe swaps it back, so every reader sees a consistent
    config with no lock on the routing hot path."""

    engine: str
    devices: int
    exchange: str
    wire_pack: bool
    delta_bits: tuple
    sieve: bool
    predict: bool
    mesh_shape: tuple
    resume_levels: int

    def degraded(self, new_devices: int) -> "MeshServeConfig":
        """This config one mesh rung down. At the single-chip floor the
        exchange knobs drop and mesh-only engines map to their
        single-chip equivalent (resilience.failover.floor_config); a
        still-multi-chip rung keeps the exchange family (the compiled
        collective program is rebuilt for the smaller mesh)."""
        if new_devices == 1:
            engine, exchange = floor_config(self.engine, self.exchange)
            return MeshServeConfig(
                engine=engine, devices=1, exchange=exchange,
                wire_pack=False, delta_bits=(), sieve=False, predict=False,
                mesh_shape=(), resume_levels=0,
            )
        return dataclasses.replace(
            self, devices=new_devices,
            # An explicit RxC factorization described the FULL mesh;
            # the degraded shape re-derives most-square.
            mesh_shape=(),
        )


class BfsService:
    """Long-lived lane-batching BFS query service over one graph.

    ``graph`` is a loaded ``Graph`` or a CLI graph spec string (path /
    ``rmat:scale=...`` / ``random:n=...``). Queries submitted from any
    thread are coalesced into packed batches of up to ``lanes`` sources
    by one scheduler thread; each batch is routed to the narrowest
    ``width_ladder`` rung that fits ("auto" builds the geometric ladder,
    "off" pins the single fixed width). With ``devices > 1`` the rungs
    are DISTRIBUTED engines spanning the mesh (ISSUE 11): wide/hybrid
    run the 1D-partition packed MS engines, ``engine='dist2d'`` the 2D
    edge partition; ``exchange``/``wire_pack``/``delta_bits``/``sieve``/
    ``predict`` pick the exchange format (PRs 5/7), ``mesh_shape`` the
    explicit RxC factorization, and the ladder floor, OOM halving grid,
    and circuit-breaker keys all become partition-aware. A MESH FAULT
    (device loss / hung collective / backend restart —
    utils/recovery.is_mesh_fault) runs the failover ladder (ISSUE 12):
    the service rebuilds its rungs on a halved mesh (down to one chip),
    re-admits the failed batch's queries, and — with
    ``mesh_probe_interval_s > 0`` — heartbeats the wider rungs in the
    background, promoting back once the mesh is healthy again;
    ``resume_levels=K`` (dist2d) adds level-checkpointed resume so the
    re-admitted queries continue from their last snapshot instead of
    the source. ``linger_ms`` bounds how long a
    partial batch waits for fill; ``queue_cap`` bounds the backlog
    (overload sheds with REJECTED); ``deadline_ms`` (default: none)
    bounds each query's QUEUE wait — see scheduler.py for the semantics.
    An OOM at rung W evicts W and every wider rung and re-admits the
    batch's queries below W (floor_lanes halving, down to 32); transient
    failures retry in place. With ``pipeline=True`` (default) result
    extraction overlaps the next batch's dispatch on a worker thread
    (``pipeline_depth`` bounds the in-flight handoff). ``distances``
    (default True) is the service-wide default for whether responses
    carry the distance table; per-query ``want_distances`` overrides, and
    distance-free queries never transfer the O(V) row off the device.
    """

    def __init__(
        self,
        graph,
        *,
        engine: str = "wide",
        lanes: int = 512,
        planes: int = DEFAULT_PLANES,
        pull_gate: bool = False,
        expand_impl: str = "xla",
        devices: int = 1,
        exchange: str = "",
        wire_pack: bool = False,
        delta_bits=(),
        sieve: bool = False,
        predict: bool = False,
        mesh_shape=(),
        resume_levels: int = 0,
        mesh_probe_interval_s: float = 0.0,
        width_ladder="auto",
        pipeline: bool = True,
        pipeline_depth: int = 2,
        linger_ms: float = 2.0,
        queue_cap: int = 1024,
        deadline_ms: float = 0.0,
        max_retries: int = 2,
        max_requeues: int = 8,
        watchdog_ms: float = 0.0,
        breaker_threshold: int = 3,
        breaker_cooldown_ms: float = 30_000.0,
        audit_rate: float = 0.0,
        audit_structural: bool = False,
        audit_checksum: bool = False,
        audit_seed: int = 0,
        cache_bytes: int = 0,
        landmarks: int = 0,
        dynamic=(),
        generation_dir: str | None = None,
        staleness_bound: int = 0,
        single_flight: bool = True,
        distances: bool = True,
        kinds=None,
        registry: EngineRegistry | None = None,
        registry_capacity: int = 4,
        aot_dir: str | None = None,
        autostart: bool = True,
        log=None,
    ):
        self._log = log or (lambda msg: None)
        # Widths and the degrade cap share one lock: the scheduler routes
        # while the extraction worker may be shrinking the ladder after a
        # fetch-time OOM.
        self._width_lock = threading.Lock()
        self._ladder = build_width_ladder(  # guarded-by: _width_lock
            lanes, width_ladder, devices=devices, engine=engine
        )
        self._max_lanes = self._ladder[-1]  # guarded-by: _width_lock
        # The engine/mesh width grid (ISSUE 11): the OOM halving ladder
        # quantizes onto it and stops at its floor — a mesh service never
        # degrades into widths no partition benefits from (or, for the
        # hybrid engines, widths that cannot even build).
        self._width_floor, self._width_quantum = ladder_bounds(
            lanes, devices=devices, engine=engine
        )
        # An internally-created registry must hold the WHOLE ladder
        # resident (plus one degrade-rung slot) or routing thrashes
        # rebuilds; a caller-supplied registry keeps its own policy.
        # ``aot_dir`` arms the registry's artifact store (the --preheat
        # path, ISSUE 9): every rung whose artifacts are present adopts
        # deserialized executables instead of compiling.
        self._registry = registry or EngineRegistry(
            capacity=max(registry_capacity, len(self._ladder) + 1),
            log=self._log,
            aot_store=aot_dir,
        )
        if isinstance(graph, str):
            self._graph_key = graph
        else:
            self._graph_key = f"graph@{id(graph):x}"
            self._registry.add_graph(self._graph_key, graph)
        self._graph = self._registry.graph(self._graph_key)
        self._planes = planes
        self._pull_gate = pull_gate
        self._expand_impl = expand_impl
        # The CURRENT engine/mesh config: one immutable object swapped
        # atomically by the mesh failover ladder (degrade) and the
        # health probe (restore) — see MeshServeConfig. _cfg0 is the
        # as-launched config a restore climbs back to; _ladder_arg lets
        # the degraded width ladder re-derive from the operator's
        # original intent at the new device count (topped by the
        # current _max_lanes so an OOM cap survives the failover).
        self._mesh_cfg = self._cfg0 = MeshServeConfig(
            engine=engine, devices=devices, exchange=exchange,
            wire_pack=bool(wire_pack), delta_bits=tuple(delta_bits),
            sieve=bool(sieve), predict=bool(predict),
            mesh_shape=tuple(mesh_shape),
            resume_levels=int(resume_levels),
        )
        self._ladder_arg = width_ladder
        self._mesh_probe_interval_s = max(mesh_probe_interval_s, 0.0)
        self._mesh_probe = None  # guarded-by: _lock (lifecycle state)
        # Served query kinds (ISSUE 14; full-mesh serving ISSUE 20):
        # None = everything this engine/mesh/graph supports
        # (workloads.supported_kinds — sssp needs a weights plane, p2p
        # an undirected graph; on a mesh the kinds ride the wide/dist2d
        # substrates). An explicit list is validated here, at
        # construction.
        auto_kinds = supported_kinds(engine, devices, self._graph)
        if kinds is None:
            self._kinds = auto_kinds
        else:
            kinds = tuple(kinds)
            for kind in kinds:
                if kind not in KINDS:
                    raise ValueError(
                        f"unknown kind {kind!r} (one of {KINDS})"
                    )
                if kind not in auto_kinds:
                    why = kind_unsupported_reason(
                        kind, engine, devices, self._graph
                    )
                    raise ValueError(
                        f"kind {kind!r} is not servable by this config: "
                        f"{why} (servable: {auto_kinds})"
                    )
            self._kinds = kinds
        if not self._kinds:
            raise ValueError("service must serve at least one kind")
        # Dynamic-graph tier (ISSUE 19): ``dynamic=(rows, kcap)`` (or
        # True for the default capacity) arms streaming edge updates —
        # every engine builds with a bounded overlay of that shape, the
        # flip lock serializes mutation flips against batch dispatch,
        # and ``apply_edge_updates`` becomes the mutation API. The flip
        # state below exists (cheap, inert) even on static services so
        # the scheduler loop stays branch-free.
        self._flip_lock = threading.RLock()
        self._dynamic = None
        self._gen_store = None
        self._gen_tmp = None
        self._overlay_cap = ()
        # Writes serialize under _flip_lock; reads are deliberately
        # lock-free (a torn-free CPython int snapshot) — _spec and the
        # cache straggler guard run on paths that also hold _width_lock,
        # and taking the flip lock there would close a lock-order cycle.
        self._graph_generation = 0
        self._overlay_tables = None  # guarded-by: _flip_lock
        self._overlay_epoch = 0  # guarded-by: _flip_lock
        self._flips = 0  # guarded-by: _flip_lock
        self._compactions = 0  # guarded-by: _flip_lock
        self._flip_ms: list = []  # guarded-by: _flip_lock (last 64)
        self._staleness = None
        if dynamic:
            from tpu_bfs.graph.dynamic import (
                DEFAULT_CAPACITY,
                DynamicGraph,
                GenerationStore,
            )

            cap = (DEFAULT_CAPACITY if dynamic is True
                   else (int(dynamic[0]), int(dynamic[1])))
            self._overlay_cap = cap
            # Raises on an undirected/engine/pull_gate mismatch before
            # any build (DynamicGraph checks the base; EngineSpec
            # .validate below checks the engine combos).
            self._dynamic = DynamicGraph(
                self._graph, capacity=cap, log=self._log
            )
            if generation_dir is None:
                import tempfile

                # Service-owned store: compactions still get the full
                # crash-safe commit protocol, just not a survivable
                # location (pass generation_dir for that).
                self._gen_tmp = tempfile.TemporaryDirectory(
                    prefix="tpu-bfs-generations-"
                )
                generation_dir = self._gen_tmp.name
            self._gen_store = GenerationStore(generation_dir,
                                              log=self._log)
            if "p2p" in self._kinds:
                # p2p's path reconstruction scans the BUILD-TIME edge
                # tables (parent_scan), which the overlay fold never
                # touches — a reconstructed path could walk a removed
                # edge. Dropped from dynamic serving until the scan
                # learns the overlay (EngineSpec.validate enforces the
                # same).
                self._kinds = tuple(
                    k for k in self._kinds if k != "p2p"
                )
                self._log(
                    "dynamic serving: p2p dropped from the served kinds "
                    "(path reconstruction reads build-time edge tables)"
                )
                if not self._kinds:
                    raise ValueError(
                        "dynamic serving cannot serve p2p alone"
                    )
            if audit_rate > 0:
                from tpu_bfs.integrity.staleness import StalenessAuditor

                # The generation-staleness arm of the integrity tier:
                # same sampling rate as the shadow audits, replaying
                # against the generation ring instead of a disjoint
                # rung. Disarmed (with the rest of the audits) at
                # rate 0.
                self._staleness = StalenessAuditor(
                    rate=audit_rate, seed=audit_seed,
                    bound=staleness_bound,
                    on_over_bound=self._on_stale_generation,
                    log=self._log,
                )
                self._staleness.push_generation(0, self._graph)
        elif generation_dir is not None:
            raise ValueError(
                "generation_dir without dynamic=(rows, kcap): the "
                "generation store only exists to persist compactions"
            )
        if registry is None and len(self._kinds) > 1:
            # The internally-owned registry must hold the warmed primary
            # ladder PLUS one resident engine per additional kind (their
            # serving rungs build lazily) or multi-kind traffic thrashes
            # rebuilds; a caller-supplied registry keeps its own policy.
            self._registry.capacity = max(
                self._registry.capacity,
                len(self._ladder) + len(self._kinds),
            )
        for w in self._ladder:
            self._spec(w).validate()  # fail at construction, not first dispatch
        self._linger_s = max(linger_ms, 0.0) / 1e3
        self._default_deadline_s = max(deadline_ms, 0.0) / 1e3
        self._queue = AdmissionQueue(queue_cap)
        self.metrics = ServeMetrics()
        # Per-width circuit breaker over deterministic batch failures:
        # routing skips an open rung (see _route_width) instead of paying
        # its full retry ladder per batch; half-opens on a timer.
        self._breaker = CircuitBreaker(
            threshold=breaker_threshold,
            cooldown_s=max(breaker_cooldown_ms, 0.0) / 1e3,
            log=self._log,
        )
        self._executor = BatchExecutor(
            self.metrics, max_retries=max_retries, log=self._log,
            watchdog_s=max(watchdog_ms, 0.0) / 1e3, breaker=self._breaker,
        )
        self._max_retries = max_retries
        # Bounded OOM requeue budget: a query re-admitted more than this
        # many times resolves with an explicit error carrying its attempt
        # history instead of looping forever when every rung is broken.
        self._max_requeues = max(int(max_requeues), 0)
        # Online integrity tier (ISSUE 15, tpu_bfs/integrity): armed by
        # any audit knob — structural tree checks on every served batch,
        # sampled shadow re-execution on a disjoint rung, wire checksums
        # on the audited transfers, and corruption quarantine. Disarmed
        # services hold None and pay nothing anywhere.
        # Audit-flush barrier state: how many batches are inside the
        # finish+observe window right now (flush_audits waits for zero
        # with an empty pipeline, so counters read complete).
        self._audit_quiesce = threading.Lock()
        self._finishing = 0  # guarded-by: _audit_quiesce
        if audit_rate > 0 or audit_structural or audit_checksum:
            from tpu_bfs.integrity import IntegrityTier

            self._integrity = IntegrityTier(
                self, rate=audit_rate,
                structural=bool(audit_structural) or bool(audit_checksum),
                checksum=audit_checksum, seed=audit_seed,
            )
            if registry is None:
                # The shadow replays keep one disjoint rung (plus a
                # rebuild slot) resident next to the serving ladder; an
                # internally-owned registry must fit it or audits thrash
                # the warm rungs they exist to check.
                self._registry.capacity = self._registry.capacity + 2
        else:
            self._integrity = None
        # Answer tier (ISSUE 18). Single-flight collapsing is on by
        # default (N concurrent identical queries admit one traversal)
        # and independent of the cache knobs; ``single_flight=False``
        # exists for saturation/load harnesses that hammer one source
        # to fill lanes on purpose. The result cache and the landmark
        # distance columns are armed by their knobs. Hits bypass the
        # scheduler entirely and stamp cache_hit/landmark provenance.
        self._inflight = InflightIndex() if single_flight else None
        self._cache = (
            AnswerCache(
                graph_key=self._graph_key, max_bytes=int(cache_bytes),
                metrics=self.metrics, log=self._log,
            )
            if cache_bytes else None
        )
        self._landmark_k = max(int(landmarks), 0)
        self._landmarks = None  # built by start()'s warm-up when armed
        self._want_distances_default = bool(distances)
        self._pipe_q: _queue.Queue | None = (
            _queue.Queue(maxsize=max(1, int(pipeline_depth)))
            if pipeline else None
        )
        # _closed/_draining stay deliberately lock-free single-word flags
        # (submit must never block behind start()'s minutes-long builds),
        # hence unannotated; the thread handles are lifecycle state only
        # ever touched under the service lock.
        self._closed = False
        self._draining = False
        self._thread: threading.Thread | None = None  # guarded-by: _lock
        self._extract_thread: threading.Thread | None = None  # guarded-by: _lock
        self._lock = threading.Lock()
        if autostart:
            self.start()

    # --- lifecycle --------------------------------------------------------

    def _spec(self, width: int | None = None,
              cfg: MeshServeConfig | None = None,
              kind: str = "bfs") -> EngineSpec:
        cfg = self._mesh_cfg if cfg is None else cfg
        if kind == "sssp" and cfg.devices > 1:
            # The service-wide exchange config speaks the base family's
            # OR dialect; the distributed sssp engine exchanges under
            # (min, +) (ISSUE 20). Map the spirit of the config onto the
            # kind's own family: queue-style stays queue-style (sparse +
            # delta_bits + predict ride along), everything dense-like
            # becomes the engine default; wire_pack/sieve are OR-only
            # knobs with no min twin and drop here.
            sparse = cfg.exchange == "sparse"
            return EngineSpec(
                graph_key=self._graph_key,
                graph_generation=self._graph_generation,
                kind=kind,
                engine=cfg.engine,
                lanes=self.lanes if width is None else width,
                planes=self._planes,
                expand_impl=self._expand_impl,
                devices=cfg.devices,
                exchange=cfg.exchange if sparse else "",
                delta_bits=cfg.delta_bits if sparse else (),
                predict=cfg.predict if sparse else False,
                mesh_shape=cfg.mesh_shape,
            )
        return EngineSpec(
            graph_key=self._graph_key,
            graph_generation=self._graph_generation,
            overlay=self._overlay_cap,
            kind=kind,
            engine=cfg.engine,
            lanes=self.lanes if width is None else width,
            planes=self._planes,
            pull_gate=self._pull_gate,
            expand_impl=self._expand_impl,
            devices=cfg.devices,
            exchange=cfg.exchange,
            wire_pack=cfg.wire_pack,
            delta_bits=cfg.delta_bits,
            sieve=cfg.sieve,
            predict=cfg.predict,
            mesh_shape=cfg.mesh_shape,
            resume_levels=cfg.resume_levels,
        )

    def start(self) -> "BfsService":
        """Build-and-warm every ladder rung's engine (widest first, so
        the width most likely to OOM degrades the ladder before anything
        narrower is paid for), then start the scheduler thread and — when
        pipelining — the extraction worker. Idempotent; called by the
        constructor unless ``autostart=False`` (tests that stage queries
        before dispatch)."""
        with self._lock:
            if self._closed:
                raise RuntimeError("service is closed")
            if self._thread is not None:
                return self
            for w in sorted(self.width_ladder, reverse=True):
                if w <= self.lanes:  # rungs above a degraded cap died
                    self._acquire_engine(w, self._primary_kind)
            if self._landmark_k > 0:
                # Landmark warm-up (ISSUE 18): one flagship MS-BFS
                # batch on the cold-start path, before READY — the K
                # distance columns then answer p2p in microseconds.
                self._warm_landmarks()
            if (self._mesh_probe_interval_s > 0
                    and self._cfg0.devices > 1
                    and self._mesh_probe is None):
                from tpu_bfs.resilience.probe import MeshHealthProbe

                # Background mesh prober: heartbeats the rungs above a
                # degraded service and promotes back onto the widest
                # healthy one — the half-open side of the failover
                # ladder (no-op while the service is at full width).
                self._mesh_probe = MeshHealthProbe(
                    self._cfg0.devices,
                    interval_s=self._mesh_probe_interval_s,
                    current=lambda: self._mesh_cfg.devices,
                    on_healthy=self._on_mesh_healthy,
                    log=self._log,
                ).start()
            if self._pipe_q is not None:
                self._extract_thread = threading.Thread(
                    target=self._extract_loop, name="bfs-serve-extract",
                    daemon=True,
                )
                self._extract_thread.start()
            self._thread = threading.Thread(
                target=self._loop, name="bfs-serve-scheduler", daemon=True
            )
            self._thread.start()
            if self._integrity is not None:
                self._integrity.start()
        return self

    def drain(self) -> None:
        """Stop ADMISSION only: new submits shed with REJECTED while
        queued and in-flight queries run to resolution. The first half of
        a graceful shutdown (the JSONL server's SIGTERM path); ``close``
        completes it. Idempotent."""
        self._draining = True

    def close(self) -> None:
        """Stop serving: in-flight batches complete (the extraction
        worker drains its handoff before exiting), queued queries resolve
        with SHUTDOWN. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            thread = self._thread
            extract_thread = self._extract_thread
            probe, self._mesh_probe = self._mesh_probe, None
        if probe is not None:
            probe.stop()
        self._queue.stop()
        if thread is not None:
            thread.join()
            if extract_thread is not None:
                self._pipe_q.put(None)  # after scheduler exit: no more puts
                extract_thread.join()
            if self._integrity is not None:
                # After both serving threads: no more observe_batch
                # calls; close() drains every queued audit first, so the
                # final statsz carries complete audit counts.
                self._integrity.close()
        else:
            # Never started: drain staged queries here instead.
            for q in self._queue.next_batch(self._queue.cap, 0.0):
                if q.resolve_status(STATUS_SHUTDOWN, error="service closed"):
                    self.metrics.record_shutdown()
        if self._gen_tmp is not None:
            # Service-owned generation store (no generation_dir given):
            # reclaim it now instead of at interpreter teardown.
            try:
                self._gen_tmp.cleanup()
            except OSError:
                pass
            self._gen_tmp = None

    def __enter__(self) -> "BfsService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- client API -------------------------------------------------------

    @property
    def num_vertices(self) -> int:
        return self._graph.num_vertices

    @property
    def kinds(self) -> tuple:
        """Query kinds this service answers (ISSUE 14)."""
        return self._kinds

    @property
    def _primary_kind(self) -> str:
        """The kind whose ladder start() warms eagerly ("bfs" when
        served). Other kinds' engines build lazily on first query and
        stay resident per the registry LRU — per-kind correct because
        EngineSpec.kind keys the residency."""
        return "bfs" if "bfs" in self._kinds else self._kinds[0]

    @property
    def lanes(self) -> int:
        """Current maximum serving batch width (halves on OOM degrade)."""
        with self._width_lock:
            return self._max_lanes

    @property
    def width_ladder(self) -> list:
        """Resident dispatch widths, ascending (shrinks on OOM degrade)."""
        with self._width_lock:
            return list(self._ladder)

    def submit(self, source, *, id=None, deadline_ms: float | None = None,
               want_distances: bool | None = None, kind: str = "bfs",
               k: int | None = None,
               target: int | None = None) -> PendingQuery:
        """Enqueue one query; returns a PendingQuery whose ``result()``
        always resolves (ok / rejected / deadline_exceeded / error /
        shutdown — never a hang, never a silent drop).
        ``want_distances=False`` asks for a metadata-only answer (levels/
        reached) that never pulls the distance row off the device; None
        uses the service-wide ``distances`` default.

        ``kind`` picks the query family (ISSUE 14: bfs | sssp | cc |
        khop | p2p; the kinds this service actually serves are in
        ``self.kinds``); khop requires ``k`` (hop bound >= 0), p2p a
        ``target`` vertex. An unknown or unserved kind, a missing/bad
        parameter, or an out-of-range endpoint resolves the query with a
        STRUCTURED error — never a dropped request."""
        now = time.monotonic()
        ddl_s = (
            self._default_deadline_s
            if deadline_ms is None
            else max(deadline_ms, 0.0) / 1e3
        )
        kind = "bfs" if kind is None else kind
        if kind in METADATA_ONLY_KINDS:
            # cc/khop/p2p answer from summaries / the cached index; no
            # distance table exists to pull.
            want_distances = False
        q = PendingQuery(
            source, id=id, now=now,
            deadline=(now + ddl_s) if ddl_s > 0 else None,
            want_distances=(
                self._want_distances_default
                if want_distances is None else want_distances
            ),
            kind=kind if kind in KINDS else "bfs",
            k=k, target=target,
        )
        err = self._validate_query(kind, q, k, target)
        if err is not None:
            q.resolve_status(STATUS_ERROR, error=err)
            self.metrics.record_errors()
            return q
        # Answer tier (ISSUE 18), ahead of admission: a cache or
        # landmark hit resolves here — microseconds of host work, no
        # scheduler, no lane — and a duplicate of an in-flight query
        # becomes a single-flight follower that rides the leader's
        # dispatch. Order matters: the cache is consulted first (exact
        # stored payloads beat recomputed bounds), and single-flight
        # last (only queries that will actually admit need a leader).
        if not (self._closed or self._draining):
            if self._try_answer_tier(q):
                return q
            leader = (self._inflight.attach(q)
                      if self._inflight is not None else None)
            if leader is not None:
                self.metrics.record_single_flight()
                q.add_done_callback(self._account_follower)
                return q
        if self._closed or self._draining or not self._queue.offer(q):
            q.resolve_status(
                STATUS_REJECTED,
                error=(
                    "service closed" if self._closed
                    else "service draining" if self._draining
                    else "queue full"
                ),
            )
            self.metrics.record_rejected()
        return q

    def _validate_query(self, kind: str, q: PendingQuery,
                        k, target) -> str | None:
        """The per-kind admission contract (ISSUE 14 satellite): the
        error string for a malformed query, None when admissible. Every
        failure is a structured per-id response, never a drop."""
        if kind not in KINDS:
            return f"unknown kind {kind!r} (one of {KINDS})"
        if kind not in self._kinds:
            # Name WHY (ISSUE 20 satellite): the structural blocker when
            # there is one (engine family, mesh, missing weights plane,
            # directedness), else the service's own kinds= selection.
            why = kind_unsupported_reason(
                kind, self._mesh_cfg.engine, self._mesh_cfg.devices,
                self._graph,
            )
            return (
                f"kind {kind!r} is not served by this service: "
                + (why if why is not None else
                   f"excluded by this service's kinds= selection "
                   f"(engine={self._mesh_cfg.engine!r}, "
                   f"devices={self._mesh_cfg.devices})")
                + f"; serving {self._kinds}"
            )
        if not (0 <= q.source < self._graph.num_vertices):
            return (
                f"source {q.source} out of range "
                f"[0, {self._graph.num_vertices})"
            )
        if kind == "khop":
            if k is None or int(k) < 0:
                return f'khop needs "k" >= 0, got {k!r}'
        if kind == "p2p":
            if target is None:
                return 'p2p needs a "target" vertex id'
            if not (0 <= int(target) < self._graph.num_vertices):
                return (
                    f"target {target} out of range "
                    f"[0, {self._graph.num_vertices})"
                )
        return None

    # --- answer tier (ISSUE 18) -------------------------------------------

    def _try_answer_tier(self, q: PendingQuery) -> bool:
        """Resolve ``q`` from the answer cache or the landmark columns
        without traversing; False sends it on to single-flight and
        admission. Only EXACT landmark answers are served — a bounded
        bracket falls back to traversal so an armed service stays
        bit-identical to a disarmed one."""
        cache = self._cache
        if cache is not None:
            hit = cache.get(
                kind=q.kind, source=q.source, k=q.k, target=q.target,
                want_distances=q.want_distances,
            )
            if hit is not None:
                self._resolve_hit(q, hit)
                return True
        lm = self._landmarks
        if lm is not None and q.kind == "p2p" and lm.warmed:
            extras = lm.answer_p2p(q.source, q.target)
            if extras is not None:
                self._resolve_landmark(q, extras)
                return True
        return False

    def _resolve_hit(self, q: PendingQuery, hit: dict) -> None:
        extras = dict(hit["extras"]) if hit["extras"] else {}
        extras["cache_hit"] = True
        lat = (time.monotonic() - q.t_submit) * 1e3
        if q.resolve(QueryResult(
            id=q.id, source=q.source, status=STATUS_OK, kind=q.kind,
            distances=hit["distances"] if q.want_distances else None,
            levels=hit["levels"], reached=hit["reached"], extras=extras,
            latency_ms=lat,
            # No batch existed: 0/0 says "no lane was paid for", and the
            # gteps property correctly reports None.
            batch_lanes=0, dispatched_lanes=0, devices=hit["devices"],
        )):
            self.metrics.record_cache_hit(lat)
            self._audit_answer(q, origin="cache")

    def _resolve_landmark(self, q: PendingQuery, extras: dict) -> None:
        lat = (time.monotonic() - q.t_submit) * 1e3
        if q.resolve(QueryResult(
            id=q.id, source=q.source, status=STATUS_OK, kind=q.kind,
            extras=extras, latency_ms=lat,
            batch_lanes=0, dispatched_lanes=0,
        )):
            self.metrics.record_cache_hit(lat, landmark=True)
            self._audit_answer(q, origin="landmark")

    def _audit_answer(self, q: PendingQuery, *, origin: str) -> None:
        """Sampled shadow audit of a cache/landmark-resolved answer
        (ISSUE 18 x PR 15): the same deterministic sampler and disjoint
        replay rung as served batches, with the job tagged by origin so
        a confirmed mismatch quarantines the cache GENERATION (or drops
        the landmark tier), never a serving rung."""
        tier = self._integrity
        if tier is not None:
            tier.observe_answer(q, origin=origin)

    def _account_follower(self, q: PendingQuery) -> None:
        """Metrics for a single-flight follower's resolution: followers
        never enter the queue or a batch, so the batch-side counters
        never see them — account by terminal status here (the
        completed/rejected/... totals must still sum to submissions)."""
        r = q.result(0)
        if r.ok:
            self.metrics.record_follower_completed()
        elif r.status == STATUS_REJECTED:
            self.metrics.record_rejected()
        elif r.status == STATUS_EXPIRED:
            self.metrics.record_expired()
        elif r.status == STATUS_SHUTDOWN:
            self.metrics.record_shutdown()
        else:
            self.metrics.record_errors()

    def _warm_landmarks(self) -> None:
        """Build + warm the landmark distance columns with ONE flagship
        batch on a ladder rung (landmarks are just lanes). Degrades to
        disarmed on any failure — the tier is an optimization, and a
        service that cannot warm it must still reach READY."""
        if "p2p" not in self._kinds:
            # The tier only answers p2p (the symmetric triangle bound
            # needs an undirected graph — the same gate as the p2p
            # workload itself, so "p2p unserved" covers directed too).
            self._log(
                "landmark tier requested but p2p is not served by this "
                "config; skipping warm-up"
            )
            return
        from tpu_bfs.workloads.landmarks import LandmarkIndex

        k = min(self._landmark_k, self.lanes)
        try:
            index = LandmarkIndex(self._graph, k, metrics=self.metrics)
            engine = self._acquire_engine(
                self._route_width(index.k), "bfs"
            )
            ms = index.warm(
                lambda sources: engine.run(
                    np.asarray(sources, dtype=np.int64), time_it=False
                )
            )
            self._landmarks = index
            self._log(
                f"landmark tier warmed: K={index.k} columns in {ms:.0f}ms"
            )
        except Exception as exc:  # noqa: BLE001 — optimization, not liveness
            self._log(
                f"landmark warm-up failed ({type(exc).__name__}: "
                f"{str(exc)[:200]}); serving without the landmark tier"
            )

    def quarantine_answer_tier(self, origin: str, detail: str = "") -> None:
        """A CONFIRMED stale/corrupt cached or landmark answer (the
        shadow audit's finding). The suspect is stored state, not a
        rung: quarantine the cache generation (every resident entry
        becomes unreachable at the key level), or drop the landmark
        columns entirely — they are one batch to recompute and a wrong
        column poisons every bound it touches."""
        if origin == "landmark":
            self._landmarks = None
            self._log(
                f"landmark tier DROPPED after a confirmed stale answer"
                + (f" ({detail[:200]})" if detail else "")
            )
            rec = _obs.ACTIVE
            if rec is not None:
                rec.event("landmark_quarantine", cat="serve.cache",
                          detail=detail[:300])
                rec.flight_dump("landmark_quarantine")
            return
        if self._cache is not None:
            self._cache.quarantine_generation(detail=detail)

    # --- dynamic graphs (ISSUE 19) ----------------------------------------

    @property
    def graph_generation(self) -> int:
        """The served graph generation: bumps on every applied mutation
        batch (0 on a static service, and before the first mutation)."""
        return self._graph_generation

    def apply_edge_updates(self, add=(), remove=()) -> dict:
        """One streaming mutation batch: ``add`` edges ``(u, v)`` /
        ``(u, v, w)``, ``remove`` edges ``(u, v)``. Stages the bounded
        overlay on the host, CRC-verifies it across the hand-off, and
        flips the served generation atomically BETWEEN batches (the flip
        lock excludes the scheduler's dispatch section): the registry
        rekeys resident engines to the new generation, the answer cache
        invalidates by key, the landmark columns recompute, and the
        staleness auditor adopts the generation's host truth. When the
        batch does not fit the overlay, a COMPACTION runs first (new
        persisted base generation, every engine rebuilt over the
        verified artifact) and the batch re-applies on the empty
        overlay; a compaction failure rolls back — serving continues on
        base + overlay and the error propagates with nothing mutated.
        Thread-safe; callable from any thread (the JSONL server calls
        it from the reader thread). Returns a stats dict (generation,
        flip_ms, overlay_rows, compacted)."""
        if self._dynamic is None:
            raise RuntimeError(
                "service is static: construct with dynamic=(rows, kcap) "
                "(or --mutations) to serve edge updates"
            )
        if self._closed:
            raise RuntimeError("service is closed")
        from tpu_bfs.graph.dynamic import OverlayCapacityError

        t0 = time.monotonic()
        with self._flip_lock:
            compacted = False
            try:
                tables, stats = self._dynamic.apply(add=add, remove=remove)
            except OverlayCapacityError as exc:
                self._log(
                    f"overlay at capacity ({str(exc)[:200]}); compacting "
                    f"before applying the batch"
                )
                self._compact_locked()  # raises on failure (rolled back)
                compacted = True
                # Re-apply on the empty overlay over the new base. A
                # second capacity error (a single batch larger than the
                # whole overlay, or an edge at a still-inactive vertex)
                # is a caller error and propagates — the compaction
                # stands, nothing was mutated.
                tables, stats = self._dynamic.apply(add=add, remove=remove)
            self._install_overlay_locked(tables)
            gen = self._graph_generation
            flip_ms = (time.monotonic() - t0) * 1e3
            self._flips += 1
            self._flip_ms.append(flip_ms)
            del self._flip_ms[:-64]
            overlay_rows = stats["overlay_rows"]
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event("generation_flip", cat="serve.dynamic",
                      generation=gen, overlay_rows=overlay_rows,
                      compacted=compacted, flip_ms=round(flip_ms, 3))
        return {
            "generation": gen,
            "flip_ms": round(flip_ms, 3),
            "overlay_rows": overlay_rows,
            "compacted": compacted,
        }

    def _install_overlay_locked(self, tables) -> None:  # requires-lock: _flip_lock
        """The flip proper (caller holds the flip lock): CRC the staged
        tables across the host hand-off, then advance the generation and
        rekey every serve tier. Engines adopt the new tables lazily at
        their next acquire (_sync_engine_overlay) — the flip lock makes
        that indistinguishable from an eager swap, with no dependence on
        the registry's non-blocking resident listing."""
        from tpu_bfs.graph.dynamic import overlay_crc32

        dyn = self._dynamic
        gen = dyn.generation
        want_crc = overlay_crc32(tables)
        if _faults.ACTIVE is not None:
            # Chaos site generation_flip / corrupt_overlay: one table
            # word flips between CRC computation and installation —
            # exactly the host-memory rot window the re-check covers.
            tables, _fired = _faults.maybe_corrupt_overlay(
                tables, generation=gen
            )
        if overlay_crc32(tables) != want_crc:
            self._log(
                "staged overlay failed its CRC re-check before install "
                "— restaging from the host truth"
            )
            rec = _obs.ACTIVE
            if rec is not None:
                rec.event("overlay_corrupt", cat="serve.dynamic",
                          generation=gen)
                rec.flight_dump("overlay_corrupt")
            tables = dyn.overlay_tables()
        torn = (_faults.ACTIVE is not None
                and _faults.ACTIVE.take("generation_flip", "torn_flip",
                                        generation=gen))
        if torn:
            # Chaos site generation_flip / torn_flip: the metadata
            # advances (generation, registry keys, cache) but the DATA
            # does not — the previous tables stay installed, so every
            # answer is one flip stale while claiming the new
            # generation. Only the staleness auditor can catch this
            # (structural checks pass, a shadow replay reproduces it).
            self._log(
                "TORN FLIP injected: generation advanced without the "
                "overlay table swap"
            )
        else:
            self._overlay_tables = tables
        self._overlay_epoch += 1
        self._graph_generation = gen
        self._registry.rekey_generation(self._graph_key, gen)
        if self._cache is not None:
            self._cache.set_graph_generation(gen)
        lm = self._landmarks
        if lm is not None:
            # Satellite fix for the tier's frozen-at-warm-up staleness
            # hole: one added edge can tighten d(l, v) everywhere, so
            # the columns are disabled FIRST (no answer window over
            # stale bounds) and recomputed over the flipped engine.
            lm.invalidate()
            try:
                self._rewarm_landmarks_locked(lm)
            except Exception as exc:  # noqa: BLE001 — optimization tier
                self._landmarks = None
                self._log(
                    f"landmark re-warm failed after the flip "
                    f"({type(exc).__name__}: {str(exc)[:200]}); tier "
                    f"disabled"
                )
        if self._staleness is not None:
            self._staleness.push_generation(gen, dyn.materialize())
        tier = self._integrity
        if tier is not None and tier._structural is not None:
            # The structural auditor's edge tables must track the live
            # generation: a removed edge left in them would read a
            # CORRECT post-flip answer as an edge-slack violation. The
            # tier's generation gate sheds audits of superseded batches.
            tier._structural.rebind(dyn.materialize())

    def _rewarm_landmarks_locked(self, index) -> None:
        """Recompute the landmark columns over the flipped graph with
        one flagship batch (caller holds the flip lock, so the acquired
        engine is overlay-synced to the new generation)."""
        engine = self._acquire_engine(self._route_width(index.k), "bfs")
        index.warm(
            lambda sources: engine.run(
                np.asarray(sources, dtype=np.int64), time_it=False
            )
        )

    def _sync_engine_overlay(self, engine) -> None:
        """Bring one engine's overlay tables up to the installed epoch
        (every acquire path funnels here, under the flip lock). Engines
        build with an EMPTY armed overlay; lazily-built ones (a degrade
        rung, a shadow rung, a non-primary kind's first query) would
        otherwise silently serve the base graph after a flip — the
        per-engine epoch stamp closes that hole, and re-arms every
        engine after a restage heals a torn flip."""
        if self._dynamic is None:
            return
        with self._flip_lock:
            epoch = self._overlay_epoch
            if getattr(engine, "_overlay_epoch", 0) == epoch:
                return
            if self._overlay_tables is not None:
                engine.set_overlay(self._overlay_tables)
            engine._overlay_epoch = epoch

    def _restage_overlay(self) -> None:
        """Re-install the CURRENT overlay from the dynamic graph's host
        truth — the heal after a confirmed torn flip (or staged-table
        corruption): the epoch bump forces every engine to re-adopt the
        true tables at its next acquire."""
        with self._flip_lock:
            if self._dynamic is None:
                return
            self._overlay_tables = self._dynamic.overlay_tables()
            self._overlay_epoch += 1

    def _compact_locked(self) -> None:  # requires-lock: _flip_lock
        """Fold the overlay into a new persisted base generation (caller
        holds the flip lock). On success the registry's graph is
        replaced by the VERIFIED loaded artifact and every resident
        engine drops (their ELL tables bake the old base; rebuilds are
        lazy). On ANY failure — the compactor dying at the
        ``compaction_crash`` site, or the new artifact failing its CRC
        at load (quarantined ``.corrupt``) — the previous generation
        stays served (base + overlay), orphaned uncommitted artifacts
        are quarantined, and the error propagates to the mutation
        caller."""
        dyn = self._dynamic
        store = self._gen_store
        t0 = time.monotonic()
        try:
            new_graph = dyn.compact(store)
        except Exception as exc:
            quarantined = store.quarantine_orphans()
            err = f"{type(exc).__name__}: {str(exc)[:300]}"
            self._log(
                f"compaction FAILED ({err}); rolled back — serving "
                f"continues on the previous generation"
                + (f"; quarantined {quarantined}" if quarantined else "")
            )
            rec = _obs.ACTIVE
            if rec is not None:
                # Flight-recorder trigger naming the quarantined
                # artifact(s): the run-up to a dead compactor is exactly
                # the window worth keeping.
                rec.event("compaction_failed", cat="serve.dynamic",
                          error=err, quarantined=quarantined)
                rec.flight_dump("compaction_failed")
            raise
        self._registry.add_graph(self._graph_key, new_graph)
        self._graph = new_graph
        dropped = self._registry.drop_graph_engines(self._graph_key)
        self._overlay_tables = None
        self._overlay_epoch += 1
        self._compactions += 1
        ms = (time.monotonic() - t0) * 1e3
        self._log(
            f"compacted into base generation {store.current()} in "
            f"{ms:.0f}ms ({dropped} resident engines dropped; rebuilds "
            f"are lazy)"
        )
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event("compaction", cat="serve.dynamic",
                      base_generation=store.current(), dropped=dropped,
                      ms=round(ms, 1))

    def _on_stale_generation(self, *, query_id, kind, source,
                             served_generation, matched_generation,
                             staleness, detail) -> None:
        """A CONFIRMED over-bound stale answer (the staleness auditor's
        oracle replay). The suspect is the stale serving STATE — the old
        generation's tables still installed past a flip — not a rung:
        quarantine the old generation (flight dump naming its artifact),
        drop the answer cache's trust, and heal by restaging the true
        overlay onto every engine."""
        art = None
        if self._gen_store is not None:
            p = self._gen_store._path(matched_generation)
            art = p if os.path.exists(p) else None
        self._log(
            f"STALE GENERATION on query {query_id!r}: {detail[:300]} — "
            f"quarantining generation {matched_generation}"
            + (f" (artifact {art})" if art else "")
        )
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event("stale_generation", cat="serve.dynamic",
                      query=query_id, kind=kind, source=source,
                      served_generation=served_generation,
                      stale_generation=matched_generation,
                      staleness=staleness,
                      artifact=art or f"generation {matched_generation} "
                                      f"(in-memory overlay state)",
                      detail=detail[:300])
            rec.flight_dump("stale_generation")
        if self._cache is not None:
            # Cache entries were admitted under the torn state's keys.
            self._cache.quarantine_generation(
                detail=f"stale generation {matched_generation} served "
                       f"as {served_generation}"
            )
        self._restage_overlay()

    def query(self, source, *, timeout: float | None = None,
              deadline_ms: float | None = None,
              want_distances: bool | None = None, kind: str = "bfs",
              k: int | None = None, target: int | None = None):
        """Blocking submit-and-wait convenience."""
        return self.submit(
            source, deadline_ms=deadline_ms, want_distances=want_distances,
            kind=kind, k=k, target=target,
        ).result(timeout)

    def statsz_extras(self) -> dict:
        """Service-level observations beyond the metrics counters —
        merged into both the statsz() snapshot and the JSONL server's
        periodic/final statsz lines."""
        cfg = self._mesh_cfg
        out = {
            "breaker_open": self._breaker.open_keys(),
            "breaker_opens": self._breaker.opens,
            "draining": self._draining,
            # Mesh failover state (ISSUE 12): the CURRENT device count
            # (shrinks on degrade, recovers on restore) and the
            # level-checkpointed resume audit when armed.
            "devices": cfg.devices,
        }
        if self._expand_impl != "xla":
            # Kernel-tier config echo (ISSUE 16): which expansion tier
            # every resident engine on this line was built with.
            out["expand_impl"] = self._expand_impl
        if self._cfg0.devices > 1:
            out["mesh_degraded"] = cfg.devices < self._cfg0.devices
        if cfg.resume_levels:
            from tpu_bfs.resilience.resume import cache_for_graph

            counts = cache_for_graph(self._graph).counts()
            out["query_resumes"] = counts["resumes"]
            out["resume_snapshots"] = counts["snapshots"]
        if self._integrity is not None:
            # Integrity-tier config echo (ISSUE 15): what the audit
            # counters on this line were produced under.
            out["audit"] = self._integrity.config_summary()
        if self._cache is not None:
            # Answer-cache residency echo (ISSUE 18): what the cache_*
            # counters on this line were produced under.
            out["cache"] = self._cache.config_summary()
        lm = self._landmarks
        if lm is not None:
            out["landmarks"] = lm.config_summary()
        if self._dynamic is not None:
            # Dynamic-graph echo (ISSUE 19): what generation the
            # counters on this line were served under, how full the
            # overlay is, and the staleness-audit verdict counters.
            with self._flip_lock:
                dyn = {
                    "generation": self._graph_generation,
                    "overlay_rows": self._dynamic.overlay_rows_used(),
                    "overlay_capacity": list(self._overlay_cap),
                    "flips": self._flips,
                    "compactions": self._compactions,
                }
            if self._staleness is not None:
                dyn["staleness"] = self._staleness.stats()
            out["dynamic"] = dyn
        store = self._registry.aot_store
        if store is not None:
            # AOT preheat visibility: artifact hits vs JIT fallbacks —
            # the cold-start A/B's statsz-side record (BENCHMARKS.md
            # "Cold start and preheat").
            out["aot"] = store.counts()
        if _faults.ACTIVE is not None:
            # Chaos-harness visibility: per-kind injected-fault counts so
            # a soak can check every scheduled fault actually landed.
            out["faults"] = _faults.ACTIVE.counts()
        return out

    def export_aot(self, store=None) -> dict:
        """Export every resident (warmed) engine's compiled programs
        into an artifact store (a path, an ArtifactStore, or None for
        the registry's own) — the ``--export-aot`` path: this warmed
        server populates the store a successor ``--preheat``s from.
        Returns ``{"programs": total exported, "engines": count}``."""
        out = self._registry.export_resident(store)
        return {
            "programs": sum(len(v) for v in out.values()),
            "engines": len(out),
        }

    def statsz(self) -> dict:
        out = self.metrics.snapshot(
            queue_depth=self._queue.depth(), lanes=self.lanes,
            extra=self.statsz_extras(),
        )
        out["ladder"] = self.width_ladder
        out["kinds"] = list(self._kinds)
        out["pipeline"] = self._pipe_q is not None
        resident = self._registry.resident()
        # None: a build holds the registry lock right now (resident() is
        # deliberately non-blocking — see registry.py).
        out["resident_engines"] = None if resident is None else len(resident)
        return out

    def metricz(self) -> str:
        """The one-shot /metricz observation: statsz()'s snapshot
        through the ONE renderer (ServeMetrics.prometheus_text). The
        JSONL server's periodic ``--metricz-out`` instead renders the
        exact snapshot dict its statsz line just printed — one
        observation, two formats, never disagreeing (this one-shot form
        takes its own fresh snapshot, deliberately without
        mark_interval so it cannot consume the periodic line's
        interval-QPS window)."""
        return self.metrics.prometheus_text(snapshot=self.statsz())

    # --- scheduler thread -------------------------------------------------

    def _route_width(self, n: int, kind: str = "bfs") -> int:
        """The narrowest ladder rung that fits ``n`` queries (the cap when
        nothing does — the caller splits and re-admits the tail), skipping
        rungs whose circuit breaker is open. Breaker keys are
        (width, devices[, kind]): this service's mesh span — a rung
        tripped by the single-chip path never blackholes the same width
        here, and a broken workload adapter never blackholes the width's
        bfs engine. When EVERY candidate is open the narrowest fitting
        rung is used anyway — the breaker routes around broken rungs, it
        must never wedge the service. A p2p query occupies TWO base
        lanes, so its demand doubles against the (base-lane) rung
        widths."""
        from tpu_bfs.serve.executor import breaker_key

        need = 2 * n if kind == "p2p" else n
        with self._width_lock:
            fits = [w for w in self._ladder if w >= need] or [self._max_lanes]
        devices = self._mesh_cfg.devices
        for w in fits:
            if self._breaker.allow(breaker_key(w, devices, kind)):
                return w
        return fits[0]

    def _acquire_engine(self, width: int, kind: str = "bfs"):
        """The warmed engine for ``width`` x ``kind`` (clamped to the
        degrade cap), retrying transient build failures and degrading on
        build-time OOM (an engine build allocates the packed tables, so
        it can OOM exactly like a dispatch)."""
        attempt = 0
        while True:
            width = min(width, self.lanes)
            try:
                engine = self._registry.get(self._spec(width, kind=kind))
                self._sync_engine_overlay(engine)
                return engine
            except Exception as exc:  # noqa: BLE001 — gated by classifiers
                if is_oom_failure(exc) and self._degrade(width):
                    continue
                devices = self._mesh_cfg.devices
                if devices > 1 and is_mesh_fault(exc):
                    # A mesh death during the BUILD/warm-up itself (the
                    # engine's first collectives run in the warm batch):
                    # degrade the mesh and rebuild on the smaller shape
                    # instead of retrying into the same dead collective.
                    COUNTERS.bump("mesh_faults")
                    self.metrics.record_mesh_fault()
                    rec = _obs.ACTIVE
                    if rec is not None:
                        rec.event("mesh_fault", cat="serve.mesh",
                                  site="engine_build", devices=devices,
                                  error=f"{type(exc).__name__}: "
                                        f"{str(exc)[:120]}")
                        rec.flight_dump("mesh_fault")
                    if self._degrade_mesh(devices, exc):
                        continue
                if is_transient_failure(exc) and attempt < self._max_retries:
                    attempt += 1
                    self.metrics.record_retry()
                    COUNTERS.bump("transient_retries")
                    self._log(
                        f"transient engine-build failure (attempt "
                        f"{attempt}/{self._max_retries}): {str(exc)[:200]}"
                    )
                    time.sleep(min(0.05 * attempt, 2.0))
                    continue
                raise

    def _degrade(self, at_width: int, requeued: int = 0) -> bool:
        """Shrink the ladder after an OOM at ``at_width`` (dispatch-,
        fetch-, or build-time); False at the floor. The new cap is one
        halving below the OOM'd width; every rung >= it is evicted from
        the registry FIRST — the narrower rebuild must not have to fit
        next to the dying engines' tables, and wider rungs than an OOM'd
        width can only OOM harder. ``requeued`` is the query count the
        caller is about to re-admit, for the metrics record."""
        with self._width_lock:
            # Halve onto the engine/mesh width grid (ladder_bounds):
            # quantized to the width quantum (4096 for the hybrid
            # engines), floored at the mesh-scaled floor — the single-chip
            # halving specialized to floor=quantum=32.
            new = max(
                self._width_floor,
                (at_width // 2) // self._width_quantum * self._width_quantum,
            )
            if new >= at_width:
                # At the floor: no narrower width exists. Wider rungs can
                # only OOM harder, so still collapse the ladder onto the
                # floor — routing must stop dispatching into guaranteed
                # OOMs even though this batch's queries resolve as errors.
                dying = [w for w in self._ladder if w > at_width]
                self._ladder = [w for w in self._ladder if w <= at_width]
                self._max_lanes = at_width
            else:
                dying = [w for w in self._ladder if w > new]
                self._ladder = [w for w in self._ladder if w <= new]
                if new not in self._ladder:
                    self._ladder.append(new)
                self._max_lanes = new
        for w in dying:
            # Every served kind's engine at a dying width frees: the
            # kinds share one width ladder, and a width that OOM'd for
            # one kind's tables leaves no headroom for another's.
            for kind in self._kinds:
                self._registry.evict(self._spec(w, kind=kind))
        if new >= at_width:
            if dying:
                self._log(
                    f"OOM at the {at_width}-lane floor: ladder collapsed "
                    f"to {at_width} (evicted {dying})"
                )
            return False
        self._log(f"OOM degrade: {at_width} -> {new} lanes (cap {new})")
        COUNTERS.bump("oom_degrades")
        self.metrics.record_oom_degrade(requeued)
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event("oom_degrade", cat="serve.batch", from_width=at_width,
                      to_width=new, requeued=requeued)
        return True

    def _drop_resume_snapshots(self, queries) -> None:
        """Evict resume snapshots for queries that will never complete a
        resumable drive — terminally resolved (shed / floor errors) or
        re-admitted onto a config without resume (the single-chip
        floor). Without this their ~3x[V] host arrays (and spool files)
        would pin the per-graph cache for the process lifetime; dropping
        is always safe (resume degrades to starting over)."""
        if not self._cfg0.resume_levels:
            return
        from tpu_bfs.resilience.resume import cache_for_graph

        cache = cache_for_graph(self._graph)
        for q in queries:
            cache.drop(q.source)

    def _shed_over_budget(self, queries, at_width: int, why: str) -> list:
        """The bounded re-admission budget shared by the OOM and mesh
        failover paths: count this attempt on every query, resolve the
        over-budget ones with their attempt history, return the live
        rest."""
        live = []
        shed = 0
        for q in queries:
            q.requeues += 1
            q.attempt_widths.append(at_width)
            if q.requeues > self._max_requeues:
                if q.resolve_status(
                    STATUS_ERROR,
                    error=(
                        f"requeue budget exhausted: {q.requeues} {why} "
                        f"re-admissions (attempted widths "
                        f"{q.attempt_widths}) — every remaining rung is "
                        f"failing"
                    ),
                ):
                    shed += 1
            else:
                live.append(q)
        if shed:
            self._drop_resume_snapshots(
                [q for q in queries if q not in live]
            )
            self._log(f"shed {shed} queries at the requeue budget "
                      f"({self._max_requeues})")
            COUNTERS.bump("requeue_sheds", shed)
            self.metrics.record_requeue_shed(shed)
            self.metrics.record_errors(shed)
            rec = _obs.ACTIVE
            if rec is not None:
                # Flight-recorder trigger: queries dying at the requeue
                # budget mean every remaining rung is failing — exactly
                # the incident whose run-up the ring buffer holds.
                rec.event("requeue_shed", cat="serve.batch", shed=shed,
                          width=at_width)
                rec.flight_dump("requeue_shed")
        return live

    def _handle_batch_oom(self, queries, at_width: int, cause) -> None:
        """Degrade below the OOM'd width and re-admit, or resolve with
        explicit errors at the floor. Shared by the dispatch half (the
        scheduler thread) and the fetch half (the extraction worker).

        Re-admission carries a BOUNDED attempt budget (``max_requeues``):
        a query whose every attempted rung keeps OOMing resolves with an
        explicit error naming its attempt history instead of cycling
        through the ladder forever."""
        queries = self._shed_over_budget(queries, at_width, "OOM")
        if not queries:
            # Still account the degrade attempt below even when every
            # query shed: the rung DID fail, and routing must move off it.
            self._degrade(at_width)
            return
        if self._degrade(at_width, requeued=len(queries)):
            self._queue.requeue(queries)
            if self._queue.stopped:
                # The scheduler may already have drained and exited;
                # re-admitted queries must still resolve (exactly-once).
                n = 0
                for q in self._queue.next_batch(self._queue.cap, 0.0):
                    if q.resolve_status(
                        STATUS_SHUTDOWN, error="service closed"
                    ):
                        n += 1
                if n:
                    self.metrics.record_shutdown(n)
            return
        err = (
            f"out of memory at the minimum lane count "
            f"({at_width}): {str(cause)[:200]}"
        )
        self._log(err)
        self._drop_resume_snapshots(queries)
        n = 0
        for q in queries:
            if q.resolve_status(STATUS_ERROR, error=err):
                n += 1
        if n:
            self.metrics.record_errors(n)

    # --- mesh failover (ISSUE 12) -----------------------------------------

    def _degrade_mesh(self, at_devices: int, cause,
                      requeued: int = 0) -> bool:
        """Rebuild the serving ladder one MESH rung down after a mesh
        fault at ``at_devices`` (full -> half -> ... -> single chip).
        True when the service now serves from a smaller (or
        concurrently-degraded) mesh and re-admission makes sense; False
        only at the single-chip floor. The rebuild is an eviction plus
        a config swap: the next dispatch builds — or AOT-adopts, when
        the store holds the degraded shape's artifacts (utils/aot keys
        on ``devices``) — engines for the smaller mesh through the
        ordinary registry path, while the (width, devices) breaker keys
        the fault fed keep routing off the dead shape if anything
        re-offers it."""
        with self._width_lock:
            cfg = self._mesh_cfg
            if cfg.devices != at_devices:
                # Another batch already degraded (or restored) the mesh
                # out from under this fault: nothing to rebuild, but the
                # caller's queries still re-admit onto the live config.
                return True
            new_devices = next_mesh_rung(at_devices)
            if new_devices is None:
                return False
            new_cfg = cfg.degraded(new_devices)
            old_specs = [self._spec(w, cfg) for w in self._ladder]
            top = self._max_lanes  # keep any OOM degrade's width cap
            try:
                ladder = build_width_ladder(
                    top, self._ladder_arg, devices=new_devices,
                    engine=new_cfg.engine,
                )
            except ValueError:
                # The operator's explicit ladder does not fit the
                # degraded grid (e.g. an earlier OOM cap dropped its top
                # rung): re-derive geometrically rather than refuse to
                # fail over.
                ladder = build_width_ladder(
                    top, "auto", devices=new_devices, engine=new_cfg.engine,
                )
            self._mesh_cfg = new_cfg
            self._ladder = ladder
            self._max_lanes = ladder[-1]
            self._width_floor, self._width_quantum = ladder_bounds(
                top, devices=new_devices, engine=new_cfg.engine,
            )
        for spec in old_specs:
            # Free the dead mesh shape's device tables BEFORE the
            # degraded rebuilds (the OOM ladder's lesson).
            self._registry.evict(spec)
        COUNTERS.bump("mesh_degrades")
        self.metrics.record_mesh_degrade(requeued)
        self._log(
            f"MESH DEGRADE: {at_devices} -> {new_devices} devices "
            f"(engine {new_cfg.engine}, ladder {ladder}) after: "
            f"{str(cause)[:200]}"
        )
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event("mesh_degrade", cat="serve.mesh",
                      from_devices=at_devices, to_devices=new_devices,
                      engine=new_cfg.engine, ladder=list(ladder),
                      requeued=requeued)
        return True

    def _handle_mesh_fault(self, queries, at_width: int, at_devices: int,
                           cause) -> None:
        """Degrade the MESH one rung and re-admit (the failover ladder),
        sharing the OOM path's bounded requeue budget — a query bouncing
        through repeated mesh faults resolves with its attempt history
        instead of cycling forever. Reached only from mesh-spanning
        batches (the executor classifies single-chip errors as plain
        transients), so the floor branch is a never-expected backstop."""
        queries = self._shed_over_budget(queries, at_width, "mesh-fault")
        if self._degrade_mesh(at_devices, cause, requeued=len(queries)):
            if not self._mesh_cfg.resume_levels:
                # Degraded onto a config without resume (the single-chip
                # floor): the re-admitted queries complete on an engine
                # that never drops snapshots — evict theirs now.
                self._drop_resume_snapshots(queries)
            if queries:
                self._queue.requeue(queries)
                if self._queue.stopped:
                    # Same exactly-once discipline as the OOM handler.
                    n = 0
                    for q in self._queue.next_batch(self._queue.cap, 0.0):
                        if q.resolve_status(
                            STATUS_SHUTDOWN, error="service closed"
                        ):
                            n += 1
                    if n:
                        self.metrics.record_shutdown(n)
            return
        err = (
            f"mesh fault with no smaller mesh to fail over to "
            f"({at_devices} devices): {str(cause)[:200]}"
        )
        self._log(err)
        self._drop_resume_snapshots(queries)
        n = 0
        for q in queries:
            if q.resolve_status(STATUS_ERROR, error=err):
                n += 1
        if n:
            self.metrics.record_errors(n)

    def mesh_restore(self, devices: int | None = None, *,
                     probe: bool = True) -> bool:
        """Promote a degraded service back onto a wider mesh: the widest
        original-ladder rung that heartbeats healthy (or exactly
        ``devices`` when given). Engines for the restored shape rebuild
        lazily through the registry on the next dispatch. False when the
        service is not degraded or nothing wider is healthy.
        ``probe=False`` skips the heartbeat when the caller just ran it
        (the background prober's path)."""
        from tpu_bfs.resilience.failover import degrade_ladder
        from tpu_bfs.resilience.probe import mesh_heartbeat

        target0 = self._cfg0.devices
        current = self._mesh_cfg.devices
        if current >= target0:
            return False
        rungs = degrade_ladder(target0)
        if devices and int(devices) not in rungs:
            # Only the halving-ladder rungs are valid restore targets:
            # the config walk below (and the ladders/breaker keys built
            # from it) is defined rung by rung, so an off-ladder count
            # would leave cfg.devices disagreeing with the width grid.
            self._log(
                f"mesh restore: {devices} is not a failover rung of the "
                f"{target0}-device mesh ({rungs}); refusing"
            )
            return False
        candidates = (
            [int(devices)] if devices
            else [d for d in rungs if d > current]
        )
        chosen = None
        for d in candidates:
            if not (current < d <= target0):
                continue
            if probe:
                try:
                    mesh_heartbeat(d)
                except Exception as exc:  # noqa: BLE001 — dead mesh expected
                    self._log(
                        f"mesh restore: {d}-device heartbeat failed "
                        f"({type(exc).__name__}: {str(exc)[:120]})"
                    )
                    continue
            chosen = d
            break
        if chosen is None:
            return False
        with self._width_lock:
            cfg = self._mesh_cfg
            if cfg.devices >= chosen:
                return False
            new_cfg = self._cfg0
            while new_cfg.devices > chosen:
                new_cfg = new_cfg.degraded(next_mesh_rung(new_cfg.devices))
            old_specs = [self._spec(w, cfg) for w in self._ladder]
            top = self._max_lanes  # an OOM cap survives the restore
            try:
                ladder = build_width_ladder(
                    top, self._ladder_arg, devices=chosen,
                    engine=new_cfg.engine,
                )
            except ValueError:
                ladder = build_width_ladder(
                    top, "auto", devices=chosen, engine=new_cfg.engine,
                )
            self._mesh_cfg = new_cfg
            self._ladder = ladder
            self._max_lanes = ladder[-1]
            self._width_floor, self._width_quantum = ladder_bounds(
                top, devices=chosen, engine=new_cfg.engine,
            )
        for spec in old_specs:
            self._registry.evict(spec)
        self._log(
            f"MESH RESTORE: {current} -> {chosen} devices "
            f"(engine {new_cfg.engine}, ladder {ladder})"
        )
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event("mesh_restore", cat="serve.mesh",
                      from_devices=current, to_devices=chosen,
                      engine=new_cfg.engine)
        return True

    def _on_mesh_healthy(self, devices: int) -> None:
        """The background prober's promotion hook (it already ran the
        heartbeat on ``devices``)."""
        self.mesh_restore(devices, probe=False)

    # --- integrity tier (ISSUE 15) ----------------------------------------

    def _quarantine_rung(self, width: int, kind: str) -> None:
        """Corruption quarantine: evict the suspect rung (the rebuild
        clears wedged device state and recompiles) and force-open its
        (width, devices, kind) breaker so routing stops offering it until
        the cooldown's probe batch. The breaker's existing
        every-candidate-open backstop still applies — a single-rung
        service keeps serving through the rebuilt engine rather than
        wedging."""
        from tpu_bfs.serve.executor import breaker_key

        devices = self._mesh_cfg.devices
        self._registry.evict(self._spec(width, kind=kind))
        self._breaker.trip(breaker_key(width, devices, kind))

    def _escalate_mesh(self, devices: int, cause) -> None:
        """Repeated device-attributed corruption -> the PR 11 mesh
        degrade ladder: a mesh whose answers keep failing audits after
        rung rebuilds is a hardware incident, handled exactly like a
        mesh death (smaller mesh, re-warmed engines, probe-gated
        restore)."""
        if devices > 1:
            self._degrade_mesh(devices, cause)

    def _shadow_spec(self, width: int, kind: str) -> EngineSpec:
        """The DISJOINT engine config a shadow replay of a ``width``-lane
        ``kind`` answer runs on — a different compiled program, so a
        miscompiled or corrupted serving rung cannot re-produce its own
        wrong answer: another ladder rung when one exists, else the
        alternate exchange family on a mesh (a different collective
        program over the same devices), else a width off the ladder."""
        others = [w for w in self.width_ladder if w != width]
        if others:
            return self._spec(others[0], kind=kind)
        cfg = self._mesh_cfg
        if cfg.devices > 1:
            alt = {
                "": "allreduce", "ring": "allreduce", "allreduce": "ring",
            }.get(cfg.exchange) if cfg.engine == "dist2d" else {
                "": "sparse", "dense": "sparse", "sparse": "dense",
                "sliced": "dense",
            }.get(cfg.exchange)
            if alt:
                return dataclasses.replace(
                    self._spec(width, kind=kind), exchange=alt,
                    wire_pack=False, delta_bits=(), sieve=False,
                    predict=False,
                )
        floor, quantum = self._width_floor, self._width_quantum
        w2 = max(floor, (width // 2) // quantum * quantum)
        if w2 == width:
            w2 = width + quantum
        return self._spec(w2, kind=kind)

    def _acquire_shadow_engine(self, width: int, kind: str):
        """The shadow auditor's engine hook: warm (and keep resident) the
        disjoint rung through the ordinary registry path. The overlay
        sync matters here too — a shadow replay must run against the
        SERVED generation or every audited answer on a dynamic service
        would spuriously mismatch."""
        engine = self._registry.get(self._shadow_spec(width, kind))
        self._sync_engine_overlay(engine)
        return engine

    def flush_audits(self, timeout: float = 60.0) -> bool:
        """Barrier: every enqueued shadow audit processed (bench/smoke
        callers read the audit counters after this). True when armed and
        fully flushed, or trivially when disarmed."""
        if self._integrity is None:
            return True
        return self._integrity.flush(timeout)

    def _finish(self, pending) -> None:
        """The extraction half, wherever it runs (inline or worker).
        Never lets an exception escape with queries unresolved: an error
        the executor's classifier didn't translate (e.g. a device failure
        inside result extraction itself) still resolves the batch with
        explicit errors — the exactly-once bar."""
        with self._audit_quiesce:
            self._finishing += 1
        try:
            self._executor.finish_batch(pending)
            self._populate_cache(pending)
            if self._staleness is not None:
                # Generation-staleness arm (ISSUE 19): sampled oracle
                # replay against the generation ring, synchronous on
                # this worker, sealed internally like observe_batch.
                self._staleness.observe_batch(pending)
            tier = self._integrity
            if tier is not None:
                # The audit hook (ISSUE 15): every query of this batch is
                # already resolved, so audits add zero client latency;
                # observe_batch catches everything internally — an audit
                # bug must never turn a served batch into an incident.
                tier.observe_batch(pending)
        except OomRequeue as exc:
            width = pending.lanes
            # Drop the references to the OOM'd engine before the narrower
            # rebuild (the registry eviction in _degrade frees the tables
            # only once nothing else holds them).
            pending.engine = None
            pending.handle = None
            self._handle_batch_oom(exc.queries, width, exc.cause)
        except MeshFaultRequeue as exc:
            width = pending.lanes
            # Same reference discipline: the dead mesh shape's engines
            # evict during the degrade and their tables must free.
            pending.engine = None
            pending.handle = None
            self._handle_mesh_fault(exc.queries, width, exc.devices,
                                    exc.cause)
        except Exception as exc:  # noqa: BLE001 — resolve, never strand
            err = f"{type(exc).__name__}: {str(exc)[:300]}"
            self._log(f"batch extraction failed: {err}")
            rec = _obs.ACTIVE
            if rec is not None:
                # Flight-recorder trigger: an error the executor's
                # classifier did not translate is by definition the
                # unexpected kind — dump the run-up.
                rec.event("executor_error", cat="serve.batch",
                          batch=getattr(pending, "bid", None), error=err,
                          queries=[q.id for q in pending.queries])
                rec.flight_dump("executor_error")
            n = 0
            for q in pending.queries:
                if q.resolve_status(STATUS_ERROR, error=err):
                    n += 1  # idempotent: count only queries WE resolved
            if n:
                self.metrics.record_errors(n)
        finally:
            with self._audit_quiesce:
                self._finishing -= 1

    def _populate_cache(self, pending) -> None:
        """Cache-population half of the ISSUE 18 tier: AFTER a batch's
        queries resolved (extraction worker — the dispatch path never
        writes the cache), store every ok payload under the current
        generation. Best-effort by contract: a cache failure must never
        turn a served batch into an incident."""
        cache = self._cache
        if cache is None:
            return
        if (self._dynamic is not None
                and pending.generation != self.graph_generation):
            # A flip landed while this batch was in flight: its answers
            # are correct for the generation they were pinned to, but
            # caching them now would file generation G-1 payloads under
            # generation G keys — the exact staleness the key axis
            # exists to prevent. Stragglers just don't cache.
            return
        for q in pending.queries:
            try:
                r = q.result(0)
            except TimeoutError:  # a racing path owns this query
                continue
            if not r.ok:
                continue
            try:
                cache.put(
                    kind=r.kind, source=r.source, k=q.k, target=q.target,
                    want_distances=q.want_distances,
                    distances=r.distances, levels=r.levels,
                    reached=r.reached, extras=r.extras,
                    width=r.dispatched_lanes, devices=r.devices,
                )
            except Exception as exc:  # noqa: BLE001 — cache is best-effort
                self._log(
                    f"cache put failed (query {q.id!r}): "
                    f"{type(exc).__name__}: {str(exc)[:200]}"
                )

    def _extract_loop(self) -> None:
        while True:
            pending = self._pipe_q.get()
            if pending is None:
                return
            self._finish(pending)  # resolves its own failures
            # Don't pin the finished batch's engine/handle refs (device
            # tables) while idling in get() for the next one.
            pending = None  # noqa: F841 — releases device state

    def _loop(self) -> None:
        while True:
            batch = self._queue.next_batch(self.lanes, self._linger_s)
            if self._queue.stopped:
                n = 0
                for q in batch:
                    if q.resolve_status(STATUS_SHUTDOWN, error="service closed"):
                        n += 1
                if n:
                    self.metrics.record_shutdown(n)
                if not batch:
                    return
                continue
            now = time.monotonic()
            live = []
            expired = 0
            for q in batch:
                if q.expired(now):
                    if q.resolve_status(
                        STATUS_EXPIRED,
                        error="deadline expired before dispatch",
                    ):
                        expired += 1
                else:
                    live.append(q)
            if expired:
                self.metrics.record_expired(expired)
            if not live:
                continue
            try:
                # The batch is kind-uniform by construction (the queue
                # only coalesces same-batch-key queries, ISSUE 14).
                kind = getattr(live[0], "kind", "bfs")
                width = self._route_width(len(live), kind)
                rec = _obs.ACTIVE
                if rec is not None:
                    # The coalesce record: which queries formed this
                    # batch and which ladder rung routing picked — the
                    # span-chain link between admission and dispatch.
                    rec.event("coalesce", cat="serve.batch", n=len(live),
                              width=width, kind=kind,
                              queries=[q.id for q in live],
                              queue_depth=self._queue.depth())
                # The dispatch section runs under the flip lock (ISSUE
                # 19): generation flips happen BETWEEN batches, never
                # between an engine's overlay sync and its dispatch, so
                # the generation stamp below always names the tables the
                # batch actually traversed. Uncontended (and reentrant —
                # _acquire_engine syncs under it) on static services.
                with self._flip_lock:
                    engine = self._acquire_engine(width, kind)
                    if len(live) > engine.lanes:
                        # An OOM degraded the cap AFTER this batch was
                        # popped at the old one: serve what fits,
                        # re-admit the tail at the front (same contract
                        # as OomRequeue — degrade must never turn into
                        # error responses).
                        self._queue.requeue(live[engine.lanes:])
                        live = live[: engine.lanes]
                    pending = self._executor.dispatch_batch(engine, live)
                    if pending is not None:
                        pending.generation = self._graph_generation
                        pending.overlay_epoch = self._overlay_epoch
            except OomRequeue as exc:
                # Drop this frame's reference to the OOM'd engine before
                # the narrower rebuild (OomRequeue is only raised by
                # dispatch_batch, so `engine` is always bound here).
                # Ladder units (p2p's capacity counts pairs).
                width = getattr(engine, "ladder_lanes", engine.lanes)
                engine = None  # noqa: F841 — releases device tables
                self._handle_batch_oom(exc.queries, width, exc.cause)
                continue
            except MeshFaultRequeue as exc:
                width = getattr(engine, "ladder_lanes", engine.lanes)
                engine = None  # noqa: F841 — releases device tables
                self._handle_mesh_fault(exc.queries, width, exc.devices,
                                        exc.cause)
                continue
            except Exception as exc:  # noqa: BLE001 — engine build failed
                engine = None  # noqa: F841 — don't pin a half-built engine
                err = f"{type(exc).__name__}: {str(exc)[:300]}"
                self._log(f"engine unavailable: {err}")
                for q in live:
                    q.resolve_status(STATUS_ERROR, error=err)
                self.metrics.record_errors(len(live))
                continue
            if pending is not None:
                if self._pipe_q is not None:
                    # Bounded handoff: blocks when the extraction worker
                    # falls behind (pipeline_depth batches) — natural
                    # backpressure.
                    self._pipe_q.put(pending)
                else:
                    self._finish(pending)
            # This frame must not pin the batch's engine/device refs while
            # blocked in the next next_batch(): a fetch-OOM on the worker
            # may evict and rebuild narrower, and the dying tables have to
            # actually free (the same invariant the OomRequeue handler
            # documents).
            engine = pending = None  # noqa: F841 — releases device state


# --- JSONL protocol -------------------------------------------------------


def _encode_distances(d: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, d)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_distances(payload: str) -> np.ndarray:
    """Inverse of the response's ``distances_npy`` field (client helper,
    also what the tests and `make serve-smoke` round-trip through)."""
    return np.load(io.BytesIO(base64.b64decode(payload)))


def result_to_response(r, *, with_distances: bool = True) -> dict:
    out = {"id": r.id, "source": r.source, "status": r.status}
    if getattr(r, "kind", "bfs") != "bfs":
        out["kind"] = r.kind
    if r.ok:
        out["levels"] = r.levels
        out["reached"] = r.reached
        out["latency_ms"] = round(r.latency_ms, 3)
        out["batch_lanes"] = r.batch_lanes
        out["dispatched_lanes"] = r.dispatched_lanes
        if r.devices is not None and r.devices > 1:
            # Mesh-served responses carry the traversal-rate record
            # (ISSUE 11): the mesh span, this query's edge count and
            # GTEPS under the batch time share, and its share of the
            # batch's modeled exchange bytes.
            out["devices"] = r.devices
            if r.edges is not None:
                out["edges"] = r.edges
            if r.gteps is not None:
                # 6 significant digits, not fixed decimals: CPU-mesh
                # figures live around 1e-5 GTEPS and must not round to 0.
                out["gteps"] = float(f"{r.gteps:.6g}")
            if r.wire_bytes is not None:
                out["wire_bytes"] = round(r.wire_bytes, 1)
        if getattr(r, "extras", None):
            # Kind-specific fields (ISSUE 14): khop's k, cc's component
            # record, p2p's target/distance/path, sssp's weighted flag.
            # Merged last-but-reserved: protocol keys always win.
            for key, val in r.extras.items():
                out.setdefault(key, val)
        if with_distances and r.distances is not None:
            out["distances_npy"] = _encode_distances(r.distances)
    else:
        out["error"] = r.error
        if r.latency_ms is not None:
            out["latency_ms"] = round(r.latency_ms, 3)
    return out


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="tpu-bfs-serve",
        description="Lane-batching BFS query server: JSONL requests "
        '({"id":..,"source":..}) on stdin, one JSON response line each '
        "on stdout; logs and periodic statsz on stderr.",
    )
    ap.add_argument("graph", help="graph file path or generator spec "
                    "(rmat:scale=20,ef=16 | random:n=...,m=...)")
    ap.add_argument("--engine", default="wide",
                    choices=["wide", "hybrid", "packed", "dist2d"],
                    help="serving engine (default wide; hybrid needs "
                    ">= 4096 lanes; dist2d is the 2D-partition mesh "
                    "engine and needs --devices >= 2)")
    ap.add_argument("--lanes", type=int, default=512,
                    help="maximum batch width = max queries per dispatch "
                    "(multiple of 32; default 512)")
    ap.add_argument("--ladder", default="auto",
                    help="adaptive dispatch widths: 'auto' (geometric "
                    "rungs down from --lanes, e.g. 32/128/512), 'off' "
                    "(single fixed width), or an explicit list like "
                    "'32,128,512'; each batch routes to the narrowest "
                    "rung that fits (default auto)")
    ap.add_argument("--no-pipeline", action="store_true",
                    help="extract results on the scheduler thread instead "
                    "of overlapping extraction with the next dispatch")
    ap.add_argument("--pipeline-depth", type=int, default=2,
                    help="max dispatched-but-unextracted batches in "
                    "flight (default 2)")
    ap.add_argument("--planes", type=int, default=DEFAULT_PLANES,
                    choices=range(1, 9), metavar="P",
                    help=f"bit-plane count (depth cap 2**P; default "
                    f"{DEFAULT_PLANES} — serving favors depth headroom)")
    ap.add_argument("--pull-gate", action="store_true",
                    help="frontier-aware pull gate (wide/hybrid engines)")
    ap.add_argument("--expand-impl", default="xla",
                    choices=("xla", "pallas"),
                    help="pull-expansion tier (default xla): 'pallas' "
                    "serves the fused bucketed-ELL kernel "
                    "(ops/ell_expand) on the wide/hybrid engines — "
                    "bit-identical answers, one HBM write per 128-row "
                    "tile per level; a program-key axis, so --preheat/"
                    "--export-aot stores keep tiers separate")
    ap.add_argument("--devices", type=int, default=1,
                    help="shard the engine over N devices (default 1): "
                    "wide/hybrid run the 1D-partition packed MS engines, "
                    "dist2d the 2D edge partition")
    ap.add_argument("--mesh", default=None, metavar="RxC",
                    help="explicit 2D mesh shape for --engine dist2d "
                    "(e.g. 2x4; default: the most-square factorization "
                    "of --devices)")
    ap.add_argument("--exchange", default="",
                    help="mesh exchange family (engine default when "
                    "omitted): dense|sparse (wide), dense|sparse|sliced "
                    "(hybrid), ring|allreduce|sparse (dist2d)")
    ap.add_argument("--wire-pack", action="store_true",
                    help="bit-packed exchange wire format (ISSUE 5; mesh "
                    "engines — a validated no-op on the packed MS "
                    "engines, whose lane words already carry 1 bit)")
    ap.add_argument("--sparse-delta", default=None, metavar="BITS",
                    help="delta-encoded sparse-exchange ids (ISSUE 7), "
                    "e.g. '8,16'; needs --exchange sparse")
    ap.add_argument("--sparse-sieve", action="store_true",
                    help="backward visited sieve on the dist2d sparse "
                    "row exchange (ISSUE 7 planner)")
    ap.add_argument("--sparse-predict", action="store_true",
                    help="history-predictive dense selection on the "
                    "dist2d sparse row exchange (ISSUE 7 planner)")
    ap.add_argument("--resume-levels", type=int, default=0, metavar="K",
                    help="level-checkpointed query resume (ISSUE 12, "
                    "--engine dist2d): snapshot each query's loop carry "
                    "every K levels so a mid-query mesh fault resumes "
                    "from the last intact level on the degraded mesh "
                    "(bounded recompute <= K); 0 disables (default)")
    ap.add_argument("--resume-dir", default=None, metavar="DIR",
                    help="also persist resume snapshots to DIR through "
                    "the CRC checkpoint machinery (atomic writes, "
                    "quarantine on corruption), so a restarted replica "
                    "can resume too; default: in-memory only (or the "
                    "TPU_BFS_RESUME_DIR env var)")
    ap.add_argument("--mesh-probe-interval-s", type=float, default=0.0,
                    metavar="S",
                    help="background mesh health probe cadence: a "
                    "degraded service (mesh failover, ISSUE 12) "
                    "heartbeats the wider mesh rungs every S seconds "
                    "and promotes back onto the widest healthy one; "
                    "0 disables (default)")
    ap.add_argument("--linger-ms", type=float, default=2.0,
                    help="max wait for batch fill before dispatching a "
                    "partial batch (default 2.0)")
    ap.add_argument("--queue-cap", type=int, default=1024,
                    help="admission queue bound; beyond it queries are "
                    "shed with status=rejected (default 1024)")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="default per-query queue-wait deadline; 0 = none "
                    "(per-request \"deadline_ms\" overrides)")
    ap.add_argument("--max-retries", type=int, default=2,
                    help="transient-failure re-dispatches per batch "
                    "(default 2)")
    ap.add_argument("--max-requeues", type=int, default=8,
                    help="OOM re-admission budget per query; beyond it the "
                    "query resolves with an explicit error carrying its "
                    "attempt history (default 8)")
    ap.add_argument("--watchdog-ms", type=float, default=0.0,
                    help="dispatch watchdog: a batch's device fetch "
                    "exceeding this is classified as transient and "
                    "re-dispatched instead of hanging the executor; 0 "
                    "disables (default 0)")
    ap.add_argument("--breaker-threshold", type=int, default=3,
                    help="consecutive deterministic batch failures at one "
                    "width before its circuit breaker opens and routing "
                    "skips the rung (default 3)")
    ap.add_argument("--breaker-cooldown-ms", type=float, default=30000.0,
                    help="how long an open breaker waits before admitting "
                    "one half-open probe batch (default 30000)")
    ap.add_argument("--audit-rate", type=float, default=0.0, metavar="R",
                    help="online integrity tier (tpu_bfs/integrity): "
                    "replay this fraction of resolved queries on a "
                    "DISJOINT engine config (another ladder rung / the "
                    "alternate exchange family) and bit-compare; a "
                    "mismatch quarantines the serving rung (eviction + "
                    "forced-open breaker + flight dump) and repeated "
                    "device-attributed findings escalate to the mesh "
                    "degrade ladder. 0 disables (default); sampling is "
                    "deterministic in --audit-seed")
    ap.add_argument("--audit-structural", action="store_true",
                    help="structural tree checks on every served batch "
                    "(sampled lanes): the Graph500 edge-level property "
                    "for bfs, weighted relaxation for sssp, path "
                    "validity for p2p, consistency for cc/khop — the "
                    "validate.py predicates as fused device kernels")
    ap.add_argument("--audit-checksum", action="store_true",
                    help="wire checksums on the audited transfers "
                    "(integrity/wire.py): the host and device folds "
                    "over each audited distance row must agree, or the "
                    "transfer corrupted it (implies --audit-structural)")
    ap.add_argument("--audit-seed", type=int, default=0,
                    help="seed of the deterministic audit sampler "
                    "(default 0)")
    ap.add_argument("--cache-bytes", type=int, default=0, metavar="N",
                    help="answer cache (ISSUE 18): byte-budgeted LRU of "
                    "resolved payloads, CRC32-verified at every hit; "
                    "hits bypass the scheduler and stamp cache_hit "
                    "provenance. N is the payload budget in bytes "
                    "(e.g. 67108864 for 64 MB); 0 disables (default). "
                    "Single-flight dedupe of identical in-flight "
                    "queries is always on, independent of this knob")
    ap.add_argument("--landmarks", type=int, default=0, metavar="K",
                    help="landmark distance tier (ISSUE 18): warm K "
                    "high-degree landmark distance columns with one "
                    "flagship MS-BFS batch; p2p queries whose triangle "
                    "bounds meet answer exactly in microseconds, the "
                    "rest fall back to traversal. 0 disables (default); "
                    "needs p2p served (undirected graph)")
    ap.add_argument("--mutations", default=None, metavar="DxK", nargs="?",
                    const="default",
                    help="dynamic-graph serving (ISSUE 19): arm streaming "
                    "edge updates over a bounded overlay of D mutated "
                    "rows x K neighbor slots (bare --mutations uses "
                    "256x16). Requests {\"op\":\"mutate\",\"add\":[[u,v],"
                    "[u,v,w]...],\"remove\":[[u,v]...]} flip the served "
                    "generation atomically between batches; an "
                    "overflowing batch compacts into a new persisted "
                    "base generation first. Needs the single-chip wide "
                    "engine on an undirected graph; p2p drops from the "
                    "served kinds")
    ap.add_argument("--generation-dir", default=None, metavar="DIR",
                    help="persist compacted base generations here "
                    "through the CRC checkpoint machinery (atomic "
                    "writes, CURRENT pointer committed last, corrupt "
                    "artifacts quarantined .corrupt); default: a "
                    "service-owned temporary directory")
    ap.add_argument("--staleness-bound", type=int, default=0, metavar="N",
                    help="max generation flips a sampled served answer "
                    "may trail before the staleness auditor quarantines "
                    "the stale generation (needs --mutations and "
                    "--audit-rate > 0; default 0 — answers must match "
                    "the generation they were stamped with)")
    ap.add_argument("--faults", default=None, metavar="SPEC",
                    help="arm a deterministic fault-injection schedule "
                    "(tpu_bfs/faults.py), e.g. 'seed=7:transient@dispatch:"
                    "p=0.05,oom@rung=512:n=2,slow_extract:ms=200'; "
                    "default: the TPU_BFS_FAULTS env var, else disabled")
    ap.add_argument("--kinds", default=None, metavar="K1,K2,...",
                    help="query kinds to serve (ISSUE 14): any of "
                    "bfs,sssp,cc,khop,p2p; default: every kind this "
                    "engine/graph supports (sssp needs a weighted "
                    "graph, p2p an undirected one; on a mesh the kinds "
                    "ride the wide/dist2d substrates). Requests carry "
                    "{\"kind\": ...} (+ "
                    "\"k\" for khop, \"target\" for p2p); unknown or "
                    "unserved kinds answer a structured per-id error")
    ap.add_argument("--no-distances", action="store_true",
                    help="metadata-only serving by default: responses "
                    "omit distances_npy AND the distance rows are never "
                    "pulled off the device (per-request "
                    "\"want_distances\" overrides)")
    ap.add_argument("--statsz-interval-s", type=float, default=None,
                    metavar="S",
                    help="seconds between periodic telemetry emissions "
                    "(the stderr statsz line AND the --metricz-out text, "
                    "which render the same snapshot); 0 disables. "
                    "Default: the TPU_BFS_STATSZ_INTERVAL env var, else "
                    "10")
    ap.add_argument("--statsz-every", type=float, default=None,
                    help="legacy alias of --statsz-interval-s")
    ap.add_argument("--obs", default=None, metavar="SPEC", nargs="?",
                    const="1",
                    help="arm the telemetry recorder (tpu_bfs/obs): span "
                    "tracing through the serve lifecycle, per-level "
                    "engine traces, and the flight recorder (auto-dumps "
                    "the last window on watchdog trip / breaker open / "
                    "requeue shed / executor error / SIGTERM drain). "
                    "SPEC e.g. 'dump_dir=/tmp/fr,window=60'; bare --obs "
                    "uses defaults; default: the TPU_BFS_OBS env var, "
                    "else disabled")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write a Chrome/Perfetto trace-event JSON of the "
                    "whole serving session here at exit (implies --obs)")
    ap.add_argument("--metricz-out", default=None, metavar="PATH",
                    help="write the Prometheus-style /metricz text here, "
                    "atomically replaced every statsz interval and once "
                    "at exit")
    ap.add_argument("--xprof-dir", default=None, metavar="DIR",
                    help="capture a jax.profiler device trace of the "
                    "serving session into DIR, so device profiles line "
                    "up with the host spans in --trace-out")
    ap.add_argument("--registry-cap", type=int, default=4,
                    help="LRU bound on resident warmed engines (default 4, "
                    "raised automatically to fit the width ladder's rungs "
                    "plus one degrade slot)")
    ap.add_argument("--preheat", default=None, metavar="DIR",
                    help="AOT artifact store to preheat from (utils/aot): "
                    "every ladder rung whose exported programs are "
                    "present installs deserialized executables instead "
                    "of compiling, so the server reaches the READY line "
                    "without paying trace/lower/compile per rung; "
                    "stale or corrupt artifacts fall back to JIT "
                    "per program (corrupt files are quarantined)")
    ap.add_argument("--export-aot", default=None, metavar="DIR",
                    help="after warm-up, export every resident engine's "
                    "compiled programs into DIR so a successor started "
                    "with --preheat DIR skips the cold start (the warm "
                    "handoff pair — scripts/warm_handoff.py drives both "
                    "ends)")
    return ap


def _int_field(req: dict, name: str):
    """Strict integer request field (None when absent): exactly ints and
    integral floats — bool is an int subclass and json floats arrive for
    "7.0"; a lenient int() would silently truncate 7.9 to vertex 7."""
    val = req.get(name)
    if val is None:
        return None
    if isinstance(val, bool) or not isinstance(val, (int, float)):
        raise TypeError(f"{name} must be an integer, got {val!r}")
    if isinstance(val, float):
        if not val.is_integer():
            raise TypeError(f"{name} must be an integer, got {val!r}")
        val = int(val)
    return val


def _parse_request_line(line: str):
    """Parse one JSONL request into (id, source, deadline_ms, want,
    kind, k, target). Raises on ANYTHING malformed — the caller answers
    with a structured error line; nothing a client sends may kill the
    reader loop. ``kind`` is only TYPE-checked here (a string); the
    unknown-kind / kind-vs-engine / missing-parameter checks live in
    ``BfsService.submit`` so the in-process API and the wire agree on
    one contract (README protocol grammar)."""
    req = json.loads(line)
    if not isinstance(req, dict):
        raise TypeError("request must be a JSON object")
    qid = req.get("id")
    try:
        if "source" not in req:
            raise KeyError("source")
        source = _int_field(req, "source")
        if source is None:  # JSON null — absent-but-present
            raise TypeError(
                f"source must be an integer vertex id, got "
                f"{req['source']!r}"
            )
        kind = req.get("kind")
        if kind is not None and not isinstance(kind, str):
            raise TypeError(f"kind must be a string, got {kind!r}")
        k = _int_field(req, "k")
        target = _int_field(req, "target")
        ddl = req.get("deadline_ms")
        if ddl is not None:
            # Same strictness as source: float(True) == 1.0 and
            # float("100") == 100.0 would silently accept a client bug
            # and surface it later as a bogus deadline expiry.
            if isinstance(ddl, bool) or not isinstance(ddl, (int, float)):
                raise TypeError(
                    f"deadline_ms must be a JSON number, got {ddl!r}"
                )
            ddl = float(ddl)
        want = req.get("want_distances")
        if want is not None and not isinstance(want, bool):
            # bool("false") is True — a lenient coercion would silently
            # invert the client's intent.
            raise TypeError(
                f"want_distances must be a JSON boolean, got {want!r}"
            )
    except Exception as exc:
        exc._request_id = qid  # the error line must still correlate
        raise
    return qid, source, ddl, want, kind, k, target


DEFAULT_STATSZ_INTERVAL_S = 10.0


def resolve_statsz_interval(args, *, env=None) -> float:
    """The periodic-emission interval precedence (ISSUE 6 satellite):
    ``--statsz-interval-s`` wins, then the legacy ``--statsz-every``
    alias, then ``TPU_BFS_STATSZ_INTERVAL``, then 10 s. One resolved
    value drives BOTH renderings of the snapshot — the stderr statsz
    line and the ``--metricz-out`` text — so they stay on one cadence.
    An unparsable env value falls back to the default (a typo'd fleet
    variable must not kill the periodic line)."""
    interval = getattr(args, "statsz_interval_s", None)
    if interval is None:
        interval = getattr(args, "statsz_every", None)
    if interval is None:
        env_iv = (env if env is not None
                  else os.environ.get("TPU_BFS_STATSZ_INTERVAL", "")).strip()
        try:
            interval = float(env_iv) if env_iv else DEFAULT_STATSZ_INTERVAL_S
        except ValueError:
            interval = DEFAULT_STATSZ_INTERVAL_S
    return float(interval)


def run_server(args, stdin=None, stdout=None, stderr=None,
               registry=None) -> int:
    """The JSONL loop, parameterized over streams (and optionally a
    shared registry) so tests run it in-process. Reads requests until
    EOF, then drains outstanding responses, prints a final statsz line,
    and closes the service.

    LIFECYCLE (robustness issue): requests are read on a dedicated
    reader thread; the main thread waits for either the reader's normal
    EOF drain or a SIGTERM/SIGINT. A signal triggers a GRACEFUL DRAIN —
    admission stops (late submits shed REJECTED), in-flight batches
    flush, still-queued queries resolve as SHUTDOWN, every resolution is
    emitted, and the final statsz line lands — instead of the default
    die-mid-batch. Handlers are only installed when running on the main
    thread and are restored on exit, so in-process test runs are
    unaffected."""
    stdin = sys.stdin if stdin is None else stdin
    stdout = sys.stdout if stdout is None else stdout
    stderr = sys.stderr if stderr is None else stderr

    def log(msg: str) -> None:
        print(f"# {msg}", file=stderr, flush=True)

    sched = _faults.arm_from_spec_or_env(args.faults)
    if sched is not None:
        log(f"fault-injection schedule ARMED: {sched.to_spec()}")

    # Telemetry arming: --obs SPEC wins, else TPU_BFS_OBS; --trace-out
    # needs a recorder, so it arms one with defaults when nothing else
    # did. The recorder is armed BEFORE the service so registry
    # build/warm spans land in the trace (cold start is the expensive
    # part worth seeing).
    recorder = _obs.arm_for_run(getattr(args, "obs", None),
                                getattr(args, "trace_out", None))
    if recorder is not None:
        log(f"telemetry recorder ARMED (capacity "
            f"{recorder.capacity}, flight window "
            f"{recorder.window_s:.0f}s, dump dir {recorder.dump_dir!r})")
    statsz_interval = resolve_statsz_interval(args)
    xprof = getattr(args, "xprof_dir", None)
    if xprof:
        import jax

        jax.profiler.start_trace(xprof)
        log(f"jax.profiler trace started -> {xprof}")

    mesh_shape = ()
    if getattr(args, "mesh", None):
        try:
            r, c = (int(x) for x in str(args.mesh).lower().split("x"))
            mesh_shape = (r, c)
        except ValueError:
            raise SystemExit(
                f"--mesh must look like RxC (e.g. 2x4), got {args.mesh!r}"
            ) from None
    delta_raw = getattr(args, "sparse_delta", None)
    delta_bits = ()
    if delta_raw:
        try:
            delta_bits = tuple(
                int(b) for b in str(delta_raw).replace(",", " ").split()
            )
        except ValueError:
            raise SystemExit(
                f"--sparse-delta must be comma-separated bit widths "
                f"(e.g. 8,16), got {delta_raw!r}"
            ) from None
    resume_dir = getattr(args, "resume_dir", None)
    if resume_dir:
        from tpu_bfs.resilience.resume import set_default_dir

        set_default_dir(resume_dir)
    dyn_raw = getattr(args, "mutations", None)
    dynamic = ()
    if dyn_raw:
        if dyn_raw == "default":
            dynamic = True
        else:
            try:
                d, k = (int(x) for x in str(dyn_raw).lower().split("x"))
                dynamic = (d, k)
            except ValueError:
                raise SystemExit(
                    f"--mutations must look like DxK (e.g. 256x16), "
                    f"got {dyn_raw!r}"
                ) from None
    service = BfsService(
        args.graph,
        engine=args.engine,
        lanes=args.lanes,
        planes=args.planes,
        pull_gate=args.pull_gate,
        expand_impl=getattr(args, "expand_impl", "xla"),
        devices=args.devices,
        exchange=getattr(args, "exchange", "") or "",
        wire_pack=getattr(args, "wire_pack", False),
        delta_bits=delta_bits,
        sieve=getattr(args, "sparse_sieve", False),
        predict=getattr(args, "sparse_predict", False),
        mesh_shape=mesh_shape,
        resume_levels=getattr(args, "resume_levels", 0),
        mesh_probe_interval_s=getattr(args, "mesh_probe_interval_s", 0.0),
        width_ladder=args.ladder,
        pipeline=not args.no_pipeline,
        pipeline_depth=args.pipeline_depth,
        linger_ms=args.linger_ms,
        queue_cap=args.queue_cap,
        deadline_ms=args.deadline_ms,
        max_retries=args.max_retries,
        max_requeues=args.max_requeues,
        watchdog_ms=args.watchdog_ms,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_ms=args.breaker_cooldown_ms,
        audit_rate=getattr(args, "audit_rate", 0.0),
        audit_structural=getattr(args, "audit_structural", False),
        audit_checksum=getattr(args, "audit_checksum", False),
        audit_seed=getattr(args, "audit_seed", 0),
        cache_bytes=getattr(args, "cache_bytes", 0),
        landmarks=getattr(args, "landmarks", 0),
        dynamic=dynamic,
        generation_dir=getattr(args, "generation_dir", None),
        staleness_bound=getattr(args, "staleness_bound", 0),
        distances=not args.no_distances,
        kinds=(
            tuple(t for t in str(args.kinds).replace(",", " ").split())
            if getattr(args, "kinds", None) else None
        ),
        registry=registry,
        registry_capacity=args.registry_cap,
        aot_dir=getattr(args, "preheat", None),
        log=log,
    )
    export_aot = getattr(args, "export_aot", None)
    if export_aot:
        # Populate the artifact store from THIS warmed server (every
        # ladder rung is resident and compiled by now) so a successor
        # started with --preheat skips the cold start entirely.
        try:
            counts = service.export_aot(export_aot)
            log(f"aot export -> {export_aot}: {counts['programs']} "
                f"programs from {counts['engines']} engines")
        except Exception as exc:  # noqa: BLE001 — export is an optimization
            log(f"aot export failed ({exc!r}); continuing without")
    # The readiness line (stderr, like every non-protocol line): every
    # ladder rung is warmed — from artifacts when preheating — and the
    # service will now take traffic. The warm-handoff driver
    # (scripts/warm_handoff.py) keys the old server's SIGTERM on this.
    store = service._registry.aot_store
    ready_extra = ""
    if store is not None:
        c = store.counts()
        ready_extra = (f" aot_hits={c['aot_hits']}"
                       f" aot_fallbacks={c['aot_fallbacks']}")
    log(f"READY engine={args.engine} lanes={args.lanes} "
        f"ladder={service.width_ladder} "
        f"kinds={','.join(service.kinds)}{ready_extra}")
    out_lock = threading.Lock()
    outstanding = [0]
    drained = threading.Condition(out_lock)

    def emit(resp: dict) -> None:
        # Never let a dead client pipe propagate into the resolver
        # threads (a callback exception would kill the scheduler or the
        # extraction worker mid-drain).
        try:
            with out_lock:
                stdout.write(json.dumps(resp) + "\n")
                stdout.flush()
        except (OSError, ValueError) as exc:
            log(f"response emit failed ({exc!r}); dropping line")

    def on_done(q: PendingQuery) -> None:
        emit(result_to_response(q.result()))
        with drained:
            outstanding[0] -= 1
            if outstanding[0] == 0:
                drained.notify_all()

    stop = threading.Event()  # reader EOF-drain complete
    got_signal = [None]

    def on_signal(signum, frame) -> None:
        # ONLY plain attribute stores here: the handler runs on the main
        # thread between bytecodes, possibly while the interrupted frame
        # holds the stop-Event's internal (non-reentrant) lock inside
        # stop.wait() — calling stop.set() from the handler could
        # deadlock the exact shutdown it implements. The main loop polls
        # got_signal every wait timeout instead.
        got_signal[0] = signum
        service.drain()  # stop admission immediately (a plain bool store)

    old_handlers = {}
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                old_handlers[sig] = signal.signal(sig, on_signal)
            except (ValueError, OSError):  # exotic embedding: skip
                pass

    metricz_out = getattr(args, "metricz_out", None)

    def emit_telemetry() -> None:
        """ONE observation, two renderings: the stderr statsz line and
        the --metricz-out text are the same snapshot dict, so they can
        never disagree — and the interval-QPS window is consumed exactly
        once per cycle (a second snapshot microseconds later would read
        a near-empty interval and export garbage interval_qps)."""
        snap = service.metrics.snapshot(
            mark_interval=True, queue_depth=service._queue.depth(),
            lanes=service.lanes, extra=service.statsz_extras(),
        )
        print(service.metrics.statsz_line(snapshot=snap), file=stderr,
              flush=True)
        if not metricz_out:
            return
        from tpu_bfs.obs.exporters import write_metricz

        try:
            write_metricz(service.metrics.prometheus_text(snapshot=snap),
                          metricz_out)
        except OSError as exc:
            log(f"metricz write failed ({exc!r})")

    stop_statsz = threading.Event()
    if statsz_interval > 0:
        def statsz_loop() -> None:
            while not stop_statsz.wait(statsz_interval):
                emit_telemetry()

        threading.Thread(
            target=statsz_loop, name="bfs-serve-statsz", daemon=True
        ).start()

    log(f"serving {args.graph!r}: engine={args.engine} lanes={args.lanes} "
        f"ladder={service.width_ladder} "
        f"pipeline={not args.no_pipeline} linger={args.linger_ms}ms "
        f"queue_cap={args.queue_cap}")

    def mutate_line(line: str) -> bool:
        """The {"op": "mutate"} request (ISSUE 19), handled ON the
        reader thread — mutations serialize with each other for free
        and apply_edge_updates flips between dispatched batches via the
        flip lock. Returns False when the line is not a mutate op (it
        falls through to the query path). Every failure answers a
        structured line; nothing here may kill the reader."""
        try:
            req = json.loads(line)
        except Exception:  # noqa: BLE001 — the query path answers it
            return False
        if not (isinstance(req, dict) and req.get("op") == "mutate"):
            return False
        qid = req.get("id")
        try:
            add = req.get("add") or ()
            remove = req.get("remove") or ()
            if not isinstance(add, (list, tuple)) or not isinstance(
                    remove, (list, tuple)):
                raise TypeError(
                    "add/remove must be arrays of [u, v] / [u, v, w]"
                )
            out = service.apply_edge_updates(
                add=[tuple(int(x) for x in e) for e in add],
                remove=[tuple(int(x) for x in e) for e in remove],
            )
            emit({"id": qid, "op": "mutate", "ok": True, **out})
        except Exception as exc:  # noqa: BLE001 — answered, never fatal
            emit({
                "id": qid, "op": "mutate", "ok": False,
                "error": f"{type(exc).__name__}: {str(exc)[:300]}",
            })
        return True

    def reader() -> None:
        try:
            for line in stdin:
                line = line.strip()
                if not line:
                    continue
                if '"op"' in line and mutate_line(line):
                    continue
                qid = None
                try:
                    try:
                        (qid, source, ddl, want,
                         kind, k, target) = _parse_request_line(line)
                    except Exception as exc:  # noqa: BLE001 — answered, never fatal
                        # Includes RecursionError from hostile nesting and
                        # any parser surprise: one bad line must get one
                        # structured error response, never kill the loop.
                        emit({
                            "id": getattr(exc, "_request_id", None),
                            "status": STATUS_ERROR,
                            "error": f"bad request: {exc!r}",
                        })
                        continue
                    with drained:
                        outstanding[0] += 1
                    try:
                        service.submit(
                            source, id=qid, deadline_ms=ddl,
                            want_distances=want,
                            # None = absent = bfs; an empty or unknown
                            # string flows through to submit's structured
                            # unknown-kind error (never silently bfs).
                            kind="bfs" if kind is None else kind,
                            k=k, target=target,
                        ).add_done_callback(on_done)
                    except Exception:
                        # No response will ever fire for this query: the
                        # increment must be unwound or the EOF drain
                        # waits on it forever.
                        with drained:
                            outstanding[0] -= 1
                            if outstanding[0] == 0:
                                drained.notify_all()
                        raise
                except Exception as exc:  # noqa: BLE001 — keep reading
                    log(f"request line dropped ({exc!r})")
            # EOF: wait for every outstanding response, then finish.
            with drained:
                while outstanding[0] > 0 and not stop.is_set():
                    drained.wait(0.2)
        finally:
            stop.set()
            with drained:
                drained.notify_all()

    reader_t = threading.Thread(
        target=reader, name="bfs-serve-reader", daemon=True
    )
    try:
        reader_t.start()
        # Main thread parks here so signal handlers can run promptly;
        # each wait timeout polls the handler's signal flag.
        while not stop.wait(0.2):
            if got_signal[0] is not None:
                break
        if got_signal[0] is not None:
            name = signal.Signals(got_signal[0]).name
            log(f"{name} received: draining — admission stopped, flushing "
                f"in-flight batches, resolving queued queries as shutdown")
            rec = _obs.ACTIVE
            if rec is not None:
                # Flight-recorder trigger: the drain snapshot is the last
                # chance to capture what the dying process was doing.
                rec.event("signal_drain", cat="serve.lifecycle", signal=name)
                rec.flight_dump(f"{name.lower()}_drain")
    finally:
        # Drain to completion: close() flushes in-flight batches and
        # resolves still-queued queries as SHUTDOWN; their callbacks emit
        # the response lines, so wait for outstanding to hit zero (with a
        # hard bound — a graceful drain must never become a hang).
        service.close()
        deadline = time.monotonic() + 30.0
        with drained:
            while outstanding[0] > 0 and time.monotonic() < deadline:
                drained.wait(0.2)
            if outstanding[0] > 0:
                log(f"drain timeout: {outstanding[0]} responses unemitted")
        stop_statsz.set()
        emit_telemetry()  # the final statsz line + --metricz-out text
        if xprof:
            import jax

            try:
                jax.profiler.stop_trace()
                log(f"jax.profiler trace stopped -> {xprof}")
            except Exception as exc:  # noqa: BLE001 — exit path, best effort
                log(f"jax.profiler stop failed ({exc!r})")
        trace_out = getattr(args, "trace_out", None)
        if trace_out and recorder is not None:
            from tpu_bfs.obs.exporters import write_perfetto

            # Engine level tracks ride along when any resident engine
            # recorded a per-level trace (armed runs only).
            level_traces = []
            for spec, eng in service._registry.resident_engines():
                trace = getattr(eng, "last_run_trace", None)
                if trace:
                    # Mesh-labeled tracks: a dist rung's trace names its
                    # device span so single-chip and mesh rungs of the
                    # same width stay distinguishable in the viewer.
                    label = f"{spec.engine}/w{spec.lanes}"
                    if spec.devices > 1:
                        label += f"/d{spec.devices}"
                    level_traces.append((label, trace))
            try:
                write_perfetto(
                    recorder.snapshot(), trace_out, t0=recorder.t0,
                    level_traces=level_traces,
                    meta={"tool": "tpu-bfs-serve", "graph": args.graph},
                )
                log(f"trace written -> {trace_out}")
            except OSError as exc:
                log(f"trace write failed ({exc!r})")
        for sig, handler in old_handlers.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass
    return 0


def main(argv=None) -> int:
    return run_server(build_arg_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
