"""The serving front-end: in-process ``BfsService`` + stdin/stdout JSONL.

``BfsService`` is the API tests and the bench drive; the JSONL loop
(``tpu-bfs-serve`` / ``python -m tpu_bfs.serve``) is the same service
behind a line protocol:

    request   {"id": 7, "source": 12345}            (+ "deadline_ms")
    response  {"id": 7, "source": 12345, "status": "ok", "levels": 6,
               "reached": 104857, "latency_ms": 18.4, "batch_lanes": 31,
               "distances_npy": "<base64 .npy bytes>"}

Non-ok responses carry ``status`` in {rejected, deadline_exceeded,
error, shutdown} plus ``error``. Responses are emitted as queries
complete (batch order, not arrival order); ``id`` is the correlation
key. stdout carries ONLY protocol lines; logs and the periodic statsz
line go to stderr.

One scheduler thread owns all device dispatch: clients only enqueue and
wait, so jax never sees concurrent dispatch from racing threads.
"""

from __future__ import annotations

import argparse
import base64
import io
import json
import sys
import threading
import time

import numpy as np

from tpu_bfs.serve.executor import BatchExecutor, OomRequeue
from tpu_bfs.serve.metrics import ServeMetrics
from tpu_bfs.serve.registry import DEFAULT_PLANES, EngineRegistry, EngineSpec
from tpu_bfs.serve.scheduler import (
    STATUS_ERROR,
    STATUS_EXPIRED,
    STATUS_REJECTED,
    STATUS_SHUTDOWN,
    AdmissionQueue,
    PendingQuery,
)
from tpu_bfs.utils.recovery import (
    COUNTERS,
    is_oom_failure,
    is_transient_failure,
)

MIN_LANES = 32


class BfsService:
    """Long-lived lane-batching BFS query service over one graph.

    ``graph`` is a loaded ``Graph`` or a CLI graph spec string (path /
    ``rmat:scale=...`` / ``random:n=...``). Queries submitted from any
    thread are coalesced into packed batches of up to ``lanes`` sources
    by one scheduler thread; ``linger_ms`` bounds how long a partial
    batch waits for fill; ``queue_cap`` bounds the backlog (overload
    sheds with REJECTED); ``deadline_ms`` (default: none) bounds each
    query's QUEUE wait — see scheduler.py for the semantics. An OOM'd
    dispatch halves the lane count (floor_lanes ladder, down to 32) and
    re-admits its queries; transient failures retry in place.
    """

    def __init__(
        self,
        graph,
        *,
        engine: str = "wide",
        lanes: int = 512,
        planes: int = DEFAULT_PLANES,
        pull_gate: bool = False,
        devices: int = 1,
        linger_ms: float = 2.0,
        queue_cap: int = 1024,
        deadline_ms: float = 0.0,
        max_retries: int = 2,
        registry: EngineRegistry | None = None,
        registry_capacity: int = 4,
        autostart: bool = True,
        log=None,
    ):
        self._log = log or (lambda msg: None)
        self._registry = registry or EngineRegistry(
            capacity=registry_capacity, log=self._log
        )
        if isinstance(graph, str):
            self._graph_key = graph
        else:
            self._graph_key = f"graph@{id(graph):x}"
            self._registry.add_graph(self._graph_key, graph)
        self._graph = self._registry.graph(self._graph_key)
        self._engine_kind = engine
        self._planes = planes
        self._pull_gate = pull_gate
        self._devices = devices
        self._lanes = lanes
        self._spec().validate()  # fail at construction, not first dispatch
        self._linger_s = max(linger_ms, 0.0) / 1e3
        self._default_deadline_s = max(deadline_ms, 0.0) / 1e3
        self._queue = AdmissionQueue(queue_cap)
        self.metrics = ServeMetrics()
        self._executor = BatchExecutor(
            self.metrics, max_retries=max_retries, log=self._log
        )
        self._max_retries = max_retries
        self._closed = False
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        if autostart:
            self.start()

    # --- lifecycle --------------------------------------------------------

    def _spec(self) -> EngineSpec:
        return EngineSpec(
            graph_key=self._graph_key,
            engine=self._engine_kind,
            lanes=self._lanes,
            planes=self._planes,
            pull_gate=self._pull_gate,
            devices=self._devices,
        )

    def start(self) -> "BfsService":
        """Build-and-warm the serving engine, then start the scheduler
        thread. Idempotent; called by the constructor unless
        ``autostart=False`` (tests that stage queries before dispatch)."""
        with self._lock:
            if self._closed:
                raise RuntimeError("service is closed")
            if self._thread is not None:
                return self
            self._acquire_engine()  # pay the build+warm before serving
            self._thread = threading.Thread(
                target=self._loop, name="bfs-serve-scheduler", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        """Stop serving: in-flight batch completes, queued queries
        resolve with SHUTDOWN. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            thread = self._thread
        self._queue.stop()
        if thread is not None:
            thread.join()
        else:
            # Never started: drain staged queries here instead.
            for q in self._queue.next_batch(self._queue.cap, 0.0):
                if q.resolve_status(STATUS_SHUTDOWN, error="service closed"):
                    self.metrics.record_shutdown()

    def __enter__(self) -> "BfsService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- client API -------------------------------------------------------

    @property
    def num_vertices(self) -> int:
        return self._graph.num_vertices

    @property
    def lanes(self) -> int:
        """Current serving batch width (halves on OOM degrade)."""
        return self._lanes

    def submit(self, source, *, id=None, deadline_ms: float | None = None
               ) -> PendingQuery:
        """Enqueue one query; returns a PendingQuery whose ``result()``
        always resolves (ok / rejected / deadline_exceeded / error /
        shutdown — never a hang, never a silent drop)."""
        now = time.monotonic()
        ddl_s = (
            self._default_deadline_s
            if deadline_ms is None
            else max(deadline_ms, 0.0) / 1e3
        )
        q = PendingQuery(
            source, id=id, now=now,
            deadline=(now + ddl_s) if ddl_s > 0 else None,
        )
        if not (0 <= q.source < self._graph.num_vertices):
            q.resolve_status(
                STATUS_ERROR,
                error=f"source {q.source} out of range "
                      f"[0, {self._graph.num_vertices})",
            )
            self.metrics.record_errors()
            return q
        if self._closed or not self._queue.offer(q):
            q.resolve_status(
                STATUS_REJECTED,
                error="service closed" if self._closed else "queue full",
            )
            self.metrics.record_rejected()
        return q

    def query(self, source, *, timeout: float | None = None,
              deadline_ms: float | None = None):
        """Blocking submit-and-wait convenience."""
        return self.submit(source, deadline_ms=deadline_ms).result(timeout)

    def statsz(self) -> dict:
        out = self.metrics.snapshot(
            queue_depth=self._queue.depth(), lanes=self._lanes
        )
        resident = self._registry.resident()
        # None: a build holds the registry lock right now (resident() is
        # deliberately non-blocking — see registry.py).
        out["resident_engines"] = None if resident is None else len(resident)
        return out

    # --- scheduler thread -------------------------------------------------

    def _acquire_engine(self):
        """The serving engine for the CURRENT lane count, retrying
        transient build failures and degrading on build-time OOM (an
        engine build allocates the packed tables, so it can OOM exactly
        like a dispatch)."""
        attempt = 0
        while True:
            try:
                return self._registry.get(self._spec())
            except Exception as exc:  # noqa: BLE001 — gated by classifiers
                if is_oom_failure(exc) and self._degrade():
                    continue
                if is_transient_failure(exc) and attempt < self._max_retries:
                    attempt += 1
                    self.metrics.record_retry()
                    COUNTERS.bump("transient_retries")
                    self._log(
                        f"transient engine-build failure (attempt "
                        f"{attempt}/{self._max_retries}): {str(exc)[:200]}"
                    )
                    time.sleep(min(0.05 * attempt, 2.0))
                    continue
                raise

    def _degrade(self, requeued: int = 0) -> bool:
        """Halve the serving lane count after an OOM (dispatch- or
        build-time); False at the floor. ``requeued`` is the query count
        the caller is about to re-admit, for the metrics record. The
        OOM'd width's engine is evicted from the registry first: the
        narrower rebuild must not have to fit next to the dying engine's
        tables, and every wider rung would otherwise stay pinned in HBM."""
        from tpu_bfs.algorithms._packed_common import floor_lanes

        new = floor_lanes(max(MIN_LANES, self._lanes // 2))
        if new >= self._lanes:
            return False
        self._registry.evict(self._spec())
        self._log(f"OOM degrade: {self._lanes} -> {new} lanes")
        self._lanes = new
        COUNTERS.bump("oom_degrades")
        self.metrics.record_oom_degrade(requeued)
        return True

    def _loop(self) -> None:
        while True:
            batch = self._queue.next_batch(self._lanes, self._linger_s)
            if self._queue.stopped:
                n = 0
                for q in batch:
                    if q.resolve_status(STATUS_SHUTDOWN, error="service closed"):
                        n += 1
                if n:
                    self.metrics.record_shutdown(n)
                if not batch:
                    return
                continue
            now = time.monotonic()
            live = []
            expired = 0
            for q in batch:
                if q.expired(now):
                    if q.resolve_status(
                        STATUS_EXPIRED,
                        error="deadline expired before dispatch",
                    ):
                        expired += 1
                else:
                    live.append(q)
            if expired:
                self.metrics.record_expired(expired)
            if not live:
                continue
            try:
                engine = self._acquire_engine()
                if len(live) > engine.lanes:
                    # A build-time OOM degraded the width AFTER this batch
                    # was popped at the old one: serve what fits, re-admit
                    # the tail at the front (same contract as OomRequeue —
                    # degrade must never turn into error responses).
                    self._queue.requeue(live[engine.lanes:])
                    live = live[: engine.lanes]
                self._executor.run_batch(engine, live)
            except OomRequeue as exc:
                # Drop this frame's reference to the OOM'd engine before
                # the narrower rebuild (the registry eviction in _degrade
                # frees the tables only once nothing else holds them).
                engine = None  # noqa: F841 — releases device tables
                if self._degrade(requeued=len(exc.queries)):
                    self._queue.requeue(exc.queries)
                    continue
                err = (
                    f"out of memory at the minimum lane count "
                    f"({self._lanes}): {str(exc.cause)[:200]}"
                )
                self._log(err)
                for q in exc.queries:
                    q.resolve_status(STATUS_ERROR, error=err)
                self.metrics.record_errors(len(exc.queries))
            except Exception as exc:  # noqa: BLE001 — engine build failed
                err = f"{type(exc).__name__}: {str(exc)[:300]}"
                self._log(f"engine unavailable: {err}")
                for q in live:
                    q.resolve_status(STATUS_ERROR, error=err)
                self.metrics.record_errors(len(live))


# --- JSONL protocol -------------------------------------------------------


def _encode_distances(d: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, d)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_distances(payload: str) -> np.ndarray:
    """Inverse of the response's ``distances_npy`` field (client helper,
    also what the tests and `make serve-smoke` round-trip through)."""
    return np.load(io.BytesIO(base64.b64decode(payload)))


def result_to_response(r, *, with_distances: bool = True) -> dict:
    out = {"id": r.id, "source": r.source, "status": r.status}
    if r.ok:
        out["levels"] = r.levels
        out["reached"] = r.reached
        out["latency_ms"] = round(r.latency_ms, 3)
        out["batch_lanes"] = r.batch_lanes
        if with_distances:
            out["distances_npy"] = _encode_distances(r.distances)
    else:
        out["error"] = r.error
        if r.latency_ms is not None:
            out["latency_ms"] = round(r.latency_ms, 3)
    return out


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="tpu-bfs-serve",
        description="Lane-batching BFS query server: JSONL requests "
        '({"id":..,"source":..}) on stdin, one JSON response line each '
        "on stdout; logs and periodic statsz on stderr.",
    )
    ap.add_argument("graph", help="graph file path or generator spec "
                    "(rmat:scale=20,ef=16 | random:n=...,m=...)")
    ap.add_argument("--engine", default="wide",
                    choices=["wide", "hybrid", "packed"],
                    help="serving engine (default wide; hybrid needs "
                    ">= 4096 lanes)")
    ap.add_argument("--lanes", type=int, default=512,
                    help="batch width = max queries per dispatch "
                    "(multiple of 32; default 512)")
    ap.add_argument("--planes", type=int, default=DEFAULT_PLANES,
                    choices=range(1, 9), metavar="P",
                    help=f"bit-plane count (depth cap 2**P; default "
                    f"{DEFAULT_PLANES} — serving favors depth headroom)")
    ap.add_argument("--pull-gate", action="store_true",
                    help="frontier-aware pull gate (wide/hybrid engines)")
    ap.add_argument("--devices", type=int, default=1,
                    help="shard the engine over N devices (default 1)")
    ap.add_argument("--linger-ms", type=float, default=2.0,
                    help="max wait for batch fill before dispatching a "
                    "partial batch (default 2.0)")
    ap.add_argument("--queue-cap", type=int, default=1024,
                    help="admission queue bound; beyond it queries are "
                    "shed with status=rejected (default 1024)")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="default per-query queue-wait deadline; 0 = none "
                    "(per-request \"deadline_ms\" overrides)")
    ap.add_argument("--max-retries", type=int, default=2,
                    help="transient-failure re-dispatches per batch "
                    "(default 2)")
    ap.add_argument("--no-distances", action="store_true",
                    help="omit the distances_npy payload from responses "
                    "(metadata-only serving)")
    ap.add_argument("--statsz-every", type=float, default=10.0,
                    help="seconds between statsz lines on stderr; 0 "
                    "disables (default 10)")
    ap.add_argument("--registry-cap", type=int, default=4,
                    help="LRU bound on resident warmed engines (default 4)")
    return ap


def run_server(args, stdin=None, stdout=None, stderr=None,
               registry=None) -> int:
    """The JSONL loop, parameterized over streams (and optionally a
    shared registry) so tests run it in-process. Reads requests until
    EOF, then drains outstanding responses, prints a final statsz line,
    and closes the service."""
    stdin = sys.stdin if stdin is None else stdin
    stdout = sys.stdout if stdout is None else stdout
    stderr = sys.stderr if stderr is None else stderr

    def log(msg: str) -> None:
        print(f"# {msg}", file=stderr, flush=True)

    service = BfsService(
        args.graph,
        engine=args.engine,
        lanes=args.lanes,
        planes=args.planes,
        pull_gate=args.pull_gate,
        devices=args.devices,
        linger_ms=args.linger_ms,
        queue_cap=args.queue_cap,
        deadline_ms=args.deadline_ms,
        max_retries=args.max_retries,
        registry=registry,
        registry_capacity=args.registry_cap,
        log=log,
    )
    out_lock = threading.Lock()
    outstanding = [0]
    drained = threading.Condition(out_lock)

    def emit(resp: dict) -> None:
        with out_lock:
            stdout.write(json.dumps(resp) + "\n")
            stdout.flush()

    def on_done(q: PendingQuery) -> None:
        emit(result_to_response(
            q.result(), with_distances=not args.no_distances
        ))
        with drained:
            outstanding[0] -= 1
            if outstanding[0] == 0:
                drained.notify_all()

    stop_statsz = threading.Event()
    if args.statsz_every > 0:
        def statsz_loop() -> None:
            while not stop_statsz.wait(args.statsz_every):
                print(service.metrics.statsz_line(
                    queue_depth=service._queue.depth(), lanes=service.lanes,
                ), file=stderr, flush=True)

        threading.Thread(
            target=statsz_loop, name="bfs-serve-statsz", daemon=True
        ).start()

    log(f"serving {args.graph!r}: engine={args.engine} lanes={args.lanes} "
        f"linger={args.linger_ms}ms queue_cap={args.queue_cap}")
    try:
        for line in stdin:
            line = line.strip()
            if not line:
                continue
            qid = None
            try:
                req = json.loads(line)
                if not isinstance(req, dict):
                    raise TypeError("request must be a JSON object")
                qid = req.get("id")
                source = int(req["source"])
                ddl = req.get("deadline_ms")
                ddl = float(ddl) if ddl is not None else None
            except (ValueError, KeyError, TypeError) as exc:
                emit({
                    "id": qid,
                    "status": STATUS_ERROR,
                    "error": f"bad request: {exc!r}",
                })
                continue
            with drained:
                outstanding[0] += 1
            service.submit(
                source, id=qid, deadline_ms=ddl,
            ).add_done_callback(on_done)
        with drained:
            while outstanding[0] > 0:
                drained.wait()
    finally:
        stop_statsz.set()
        print(service.metrics.statsz_line(
            queue_depth=service._queue.depth(), lanes=service.lanes,
        ), file=stderr, flush=True)
        service.close()
    return 0


def main(argv=None) -> int:
    return run_server(build_arg_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
