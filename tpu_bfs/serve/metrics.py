"""Serve-mode observability: the /statsz counters.

The traversal engines' observability (utils/stats.py) is per-run; a
server needs per-PROCESS counters that survive across batches — QPS,
latency percentiles, batch fill ratio vs DISPATCHED width, the width
ladder's routing histogram, pad waste, extraction time, queue depth,
retries, sheds. One lock guards everything: writers are the scheduler
thread, the extraction worker, and client threads shedding at admission,
and the snapshot is read at human timescales (the periodic statsz line),
so contention is irrelevant next to a device dispatch.
"""

from __future__ import annotations

import json
import threading
import time
from collections import Counter, deque

import numpy as np

# Latency reservoir size: percentiles are computed over the most recent
# window, not all-time (a server that ran a slow cold batch an hour ago
# should not report it in p99 forever). 4096 completions cover minutes of
# saturated traffic at serving batch sizes.
LATENCY_WINDOW = 4096


class ServeMetrics:
    """Thread-safe serve counters + a bounded latency reservoir."""

    def __init__(self, *, now=time.monotonic):
        self._now = now
        self._lock = threading.Lock()
        self._t0 = now()
        self._latencies_ms: deque = deque(maxlen=LATENCY_WINDOW)
        self.completed = 0
        self.rejected = 0  # shed at admission (queue full / closed)
        self.expired = 0  # deadline passed while queued
        self.errors = 0
        self.shutdown = 0  # resolved unserved at close
        self.retries = 0  # transient-failure re-dispatches
        self.oom_degrades = 0  # lane-count halvings after OOM
        self.requeued = 0  # queries re-admitted after an OOM'd batch
        self.watchdog_trips = 0  # dispatch-watchdog deadline firings
        self.requeue_shed = 0  # queries shed at the requeue budget
        self.batches = 0
        self.lanes_used = 0  # real (non-pad) queries across all batches
        # Sum of DISPATCHED batch capacity: with the width ladder this is
        # the routed width per batch, so fill_ratio reports waste against
        # the width actually paid for, not the configured maximum.
        self.lanes_offered = 0
        self.padded_lanes_total = 0  # residual pad waste after routing
        self.batches_by_width = Counter()  # routing histogram: width -> batches
        self._extract_ms: deque = deque(maxlen=LATENCY_WINDOW)
        self.extract_ms_total = 0.0  # host extraction time across batches
        # Interval bookkeeping for the statsz line's recent-QPS figure.
        self._last_snap_t = self._t0
        self._last_snap_completed = 0

    def record_batch(self, used: int, capacity: int, latencies_ms, *,
                     extract_ms: float | None = None) -> None:
        with self._lock:
            self.batches += 1
            self.lanes_used += used
            self.lanes_offered += capacity
            self.padded_lanes_total += max(capacity - used, 0)
            self.batches_by_width[int(capacity)] += 1
            self.completed += len(latencies_ms)
            self._latencies_ms.extend(latencies_ms)
            if extract_ms is not None:
                self._extract_ms.append(extract_ms)
                self.extract_ms_total += extract_ms

    def record_rejected(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_expired(self, n: int = 1) -> None:
        with self._lock:
            self.expired += n

    def record_errors(self, n: int = 1) -> None:
        with self._lock:
            self.errors += n

    def record_shutdown(self, n: int = 1) -> None:
        with self._lock:
            self.shutdown += n

    def record_retry(self) -> None:
        with self._lock:
            self.retries += 1

    def record_oom_degrade(self, requeued: int) -> None:
        with self._lock:
            self.oom_degrades += 1
            self.requeued += requeued

    def record_watchdog_trip(self) -> None:
        with self._lock:
            self.watchdog_trips += 1

    def record_requeue_shed(self, n: int = 1) -> None:
        with self._lock:
            self.requeue_shed += n

    def snapshot(self, *, queue_depth: int | None = None,
                 lanes: int | None = None, mark_interval: bool = False,
                 extra: dict | None = None) -> dict:
        """One /statsz observation. ``interval_qps`` covers the window
        since the last ``mark_interval=True`` snapshot; only the ONE
        periodic emitter (statsz_line) passes that flag — ad-hoc
        observers (BfsService.statsz, the bench) must not reset the
        periodic line's window. ``qps`` is lifetime."""
        with self._lock:
            now = self._now()
            uptime = max(now - self._t0, 1e-9)
            interval = max(now - self._last_snap_t, 1e-9)
            interval_done = self.completed - self._last_snap_completed
            if mark_interval:
                self._last_snap_t = now
                self._last_snap_completed = self.completed
            lat = np.asarray(self._latencies_ms, dtype=np.float64)
            ext = np.asarray(self._extract_ms, dtype=np.float64)
            out = {
                "uptime_s": round(uptime, 3),
                "completed": self.completed,
                "qps": round(self.completed / uptime, 2),
                "interval_qps": round(interval_done / interval, 2),
                "p50_ms": round(float(np.percentile(lat, 50)), 3) if lat.size else None,
                "p99_ms": round(float(np.percentile(lat, 99)), 3) if lat.size else None,
                "fill_ratio": round(
                    self.lanes_used / self.lanes_offered, 4
                ) if self.lanes_offered else 0.0,
                "padded_lanes_total": self.padded_lanes_total,
                # Routing histogram (width ladder): how many batches each
                # dispatched width served. JSON keys must be strings.
                "routing": {
                    str(wd): n
                    for wd, n in sorted(self.batches_by_width.items())
                },
                "extract_p50_ms": round(
                    float(np.percentile(ext, 50)), 3
                ) if ext.size else None,
                "extract_ms_total": round(self.extract_ms_total, 3),
                "batches": self.batches,
                "rejected": self.rejected,
                "expired": self.expired,
                "errors": self.errors,
                "shutdown": self.shutdown,
                "retries": self.retries,
                "oom_degrades": self.oom_degrades,
                "requeued": self.requeued,
                "watchdog_trips": self.watchdog_trips,
                "requeue_shed": self.requeue_shed,
            }
        if queue_depth is not None:
            out["queue_depth"] = queue_depth
        if lanes is not None:
            out["lanes"] = lanes
        if extra:
            # Service-level observations riding the line (breaker state,
            # drain flag, injected-fault audit — BfsService.statsz_extras).
            out.update(extra)
        return out

    def statsz_line(self, **kw) -> str:
        """The periodic stderr line: a stable prefix + one JSON object, so
        log scrapers can grep ``statsz`` and parse the rest. The ONLY
        caller that advances the interval-QPS window."""
        return "statsz " + json.dumps(self.snapshot(mark_interval=True, **kw))
