"""Serve-mode observability: the /statsz counters and /metricz export.

The traversal engines' observability (utils/stats.py) is per-run; a
server needs per-PROCESS counters that survive across batches — QPS,
latency percentiles, batch fill ratio vs DISPATCHED width, the width
ladder's routing histogram, pad waste, extraction time, queue depth,
retries, sheds. One lock guards everything: writers are the scheduler
thread, the extraction worker, and client threads shedding at admission,
and the snapshot is read at human timescales (the periodic statsz line),
so contention is irrelevant next to a device dispatch.

Latency distributions are MERGEABLE LOG2-BUCKET HISTOGRAMS (ISSUE 6
satellite), not the old 4096-sample sliding-window ``np.percentile``
deques: exact counts over fixed bucket boundaries, so N replicas'
histograms sum into a fleet-wide distribution (the deques could only be
concatenated-and-resampled, which is not a percentile of anything), and
the same buckets drive the Prometheus exporter
(tpu_bfs/obs/exporters.prometheus_text) without a second accounting
path. The ``p50_ms``/``p99_ms`` snapshot keys keep their shape (float
ms or None) — their values are now histogram estimates with bounded
relative error (sub-bucketed octaves, clamped to the observed min/max,
so single-sample distributions report exactly), computed over a
two-generation recent window (``RECENT_WINDOW_S``) so the old deque's
recency property survives: a slow cold batch ages out of p99 instead of
haunting it for the process lifetime.
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import Counter


class Log2Histogram:
    """Exact-count histogram over log2 buckets with linear sub-buckets.

    Bucket boundaries are fixed process-independent constants (octaves
    ``2**EMIN .. 2**EMAX``, each split into ``SUB`` equal-width
    sub-buckets — the HDR-histogram shape), so histograms from different
    replicas :meth:`merge` by elementwise count addition. Quantile
    estimates interpolate inside one bucket (relative error <= 1/SUB per
    octave) and clamp to the exact observed min/max, so a single-sample
    histogram reports that sample exactly. Values at or below 0 land in
    the underflow bucket ``[0, 2**EMIN)``."""

    __slots__ = ("counts", "count", "total", "vmin", "vmax")

    SUB = 16  # sub-buckets per octave: <= 6.25% relative estimate error
    EMIN = -10  # 2**-10 ms ~ 1 us
    EMAX = 22  # 2**22 ms ~ 70 min
    NBUCKETS = (EMAX - EMIN) * SUB + 2  # + underflow and overflow

    def __init__(self):
        self.counts = [0] * self.NBUCKETS
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    def _index(self, v: float) -> int:
        if v < 2.0 ** self.EMIN:
            return 0
        if v >= 2.0 ** self.EMAX:
            return self.NBUCKETS - 1
        m, e = math.frexp(v)  # v = m * 2**e, m in [0.5, 1)
        octave = e - 1
        sub = int((v / 2.0 ** octave - 1.0) * self.SUB)
        return 1 + (octave - self.EMIN) * self.SUB + min(sub, self.SUB - 1)

    def bounds(self, i: int) -> tuple[float, float]:
        """[lo, hi) of bucket ``i``."""
        if i <= 0:
            return 0.0, 2.0 ** self.EMIN
        if i >= self.NBUCKETS - 1:
            return 2.0 ** self.EMAX, math.inf
        j = i - 1
        octave = self.EMIN + j // self.SUB
        sub = j % self.SUB
        width = 2.0 ** octave / self.SUB
        lo = 2.0 ** octave + sub * width
        return lo, lo + width

    def add(self, v: float) -> None:
        v = float(v)
        self.counts[self._index(v)] += 1
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v

    def add_many(self, values) -> None:
        for v in values:
            self.add(v)

    def merge(self, other: "Log2Histogram") -> "Log2Histogram":
        """Fold ``other``'s counts in (the multi-replica aggregation)."""
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        return self

    def percentile(self, q: float) -> float | None:
        """Estimated q-th percentile (linear interpolation inside the
        covering bucket, clamped to the observed extremes); None when
        empty."""
        if not self.count:
            return None
        target = (q / 100.0) * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            if cum + c >= target:
                lo, hi = self.bounds(i)
                if not math.isfinite(hi):
                    hi = max(self.vmax, lo)
                frac = (target - cum) / c
                est = lo + (hi - lo) * min(max(frac, 0.0), 1.0)
                return float(min(max(est, self.vmin), self.vmax))
            cum += c
        return float(self.vmax)

    def cumulative_buckets(self):
        """Prometheus exposition form: ``(upper_bound, cumulative_count)``
        at octave boundaries (+Inf last, bound None) — octave granularity
        keeps the text small while the sub-buckets keep estimates tight."""
        out = []
        cum = 0
        next_octave_end = self.SUB  # sub-bucket index (0-based past underflow)
        pending = self.counts[0]
        for j in range((self.EMAX - self.EMIN) * self.SUB):
            pending += self.counts[1 + j]
            if j + 1 == next_octave_end:
                cum += pending
                pending = 0
                octave = self.EMIN + (j + 1) // self.SUB
                if cum or out:
                    out.append((2.0 ** octave, cum))
                next_octave_end += self.SUB
        cum += pending + self.counts[-1]
        out.append((None, cum))
        return out

    def state_dict(self) -> dict:
        """JSON-portable form (exact; merge via :meth:`from_state`)."""
        return {
            "counts": {str(i): c for i, c in enumerate(self.counts) if c},
            "count": self.count,
            "total": self.total,
            "min": None if self.count == 0 else self.vmin,
            "max": None if self.count == 0 else self.vmax,
        }

    @classmethod
    def from_state(cls, state: dict) -> "Log2Histogram":
        h = cls()
        for i, c in state.get("counts", {}).items():
            h.counts[int(i)] = int(c)
        h.count = int(state.get("count", 0))
        h.total = float(state.get("total", 0.0))
        if h.count:
            h.vmin = float(state["min"])
            h.vmax = float(state["max"])
        return h


# How far back the p50/p99 SNAPSHOT keys look. The all-time histograms
# (histograms(), the Prometheus export) are monotone by design — scrapers
# difference them; the human-facing statsz percentiles instead read a
# two-generation window pair so a slow cold batch an hour ago cannot
# inflate p99 forever (the invariant the old 4096-sample deque kept by
# count, now kept by time: estimates cover the last 1-2 windows).
RECENT_WINDOW_S = 60.0


class ServeMetrics:
    """Thread-safe serve counters + mergeable latency histograms."""

    def __init__(self, *, now=time.monotonic):
        self._now = now
        self._lock = threading.Lock()
        self._t0 = now()
        self._latency_hist = Log2Histogram()  # guarded-by: _lock
        self._extract_hist = Log2Histogram()  # guarded-by: _lock
        # [current, previous] window pair behind the percentile snapshot
        # keys; rotated in place at RECENT_WINDOW_S boundaries.
        self._recent_t0 = self._t0  # guarded-by: _lock
        self._lat_recent = [Log2Histogram(), Log2Histogram()]  # guarded-by: _lock
        self._ext_recent = [Log2Histogram(), Log2Histogram()]  # guarded-by: _lock
        self.completed = 0  # guarded-by: _lock
        self.rejected = 0  # guarded-by: _lock — shed at admission
        self.expired = 0  # guarded-by: _lock — deadline passed while queued
        self.errors = 0  # guarded-by: _lock
        self.shutdown = 0  # guarded-by: _lock — resolved unserved at close
        self.retries = 0  # guarded-by: _lock — transient re-dispatches
        self.oom_degrades = 0  # guarded-by: _lock — lane halvings after OOM
        self.requeued = 0  # guarded-by: _lock — re-admitted after OOM'd batch
        self.watchdog_trips = 0  # guarded-by: _lock — watchdog firings
        self.requeue_shed = 0  # guarded-by: _lock — shed at requeue budget
        self.mesh_faults = 0  # guarded-by: _lock — mesh-death classifications
        self.mesh_degrades = 0  # guarded-by: _lock — mesh failover rebuilds
        # Integrity tier (ISSUE 15): audits completed, CONFIRMED
        # corruption findings, audit-infrastructure errors (replay/kernel
        # failures — never corruption), audits shed at the bounded
        # backlog, and rung quarantines. The lag histogram prices how far
        # behind the served answer its verdict lands (audit_p50_lag_ms).
        self.audits_run = 0  # guarded-by: _lock
        self.audit_failures = 0  # guarded-by: _lock
        self.audit_errors = 0  # guarded-by: _lock
        self.audit_dropped = 0  # guarded-by: _lock
        self.quarantines = 0  # guarded-by: _lock
        self._audit_lag_hist = Log2Histogram()  # guarded-by: _lock
        # Answer cache + landmark tier (ISSUE 18). cache_bytes is a
        # GAUGE (resident payload bytes, set by the cache after every
        # mutation); everything else is monotonic. The hit histogram
        # prices the bypass path separately from the traversal
        # latencies above — the split the bench's >=10x claim reads.
        self.cache_hits = 0  # guarded-by: _lock
        self.cache_misses = 0  # guarded-by: _lock
        self.cache_evictions = 0  # guarded-by: _lock
        self.cache_bytes = 0  # guarded-by: _lock — gauge
        self.cache_quarantines = 0  # guarded-by: _lock
        self.single_flight_collapses = 0  # guarded-by: _lock
        self.landmark_exact = 0  # guarded-by: _lock
        self.landmark_bounded = 0  # guarded-by: _lock
        self.landmark_fallback = 0  # guarded-by: _lock
        self._hit_hist = Log2Histogram()  # guarded-by: _lock
        self.batches = 0  # guarded-by: _lock
        self.lanes_used = 0  # guarded-by: _lock — real queries, all batches
        # Sum of DISPATCHED batch capacity: with the width ladder this is
        # the routed width per batch, so fill_ratio reports waste against
        # the width actually paid for, not the configured maximum.
        self.lanes_offered = 0  # guarded-by: _lock
        self.padded_lanes_total = 0  # guarded-by: _lock — residual pad waste
        self.batches_by_width = Counter()  # guarded-by: _lock — width -> batches
        self.extract_ms_total = 0.0  # guarded-by: _lock
        # Interval bookkeeping for the statsz line's recent-QPS figure.
        self._last_snap_t = self._t0  # guarded-by: _lock
        self._last_snap_completed = 0  # guarded-by: _lock

    def record_batch(self, used: int, capacity: int, latencies_ms, *,
                     extract_ms: float | None = None) -> None:
        with self._lock:
            self.batches += 1
            self.lanes_used += used
            self.lanes_offered += capacity
            self.padded_lanes_total += max(capacity - used, 0)
            self.batches_by_width[int(capacity)] += 1
            self.completed += len(latencies_ms)
            self._rotate_recent()
            self._latency_hist.add_many(latencies_ms)
            self._lat_recent[0].add_many(latencies_ms)
            if extract_ms is not None:
                self._extract_hist.add(extract_ms)
                self._ext_recent[0].add(extract_ms)
                self.extract_ms_total += extract_ms

    def _rotate_recent(self) -> None:  # requires-lock: _lock
        """Age the percentile window pair (caller holds the lock): one
        elapsed window shifts current -> previous; two or more mean
        everything recorded is stale and both drop."""
        elapsed = self._now() - self._recent_t0
        if elapsed < RECENT_WINDOW_S:
            return
        if elapsed >= 2 * RECENT_WINDOW_S:
            self._lat_recent = [Log2Histogram(), Log2Histogram()]
            self._ext_recent = [Log2Histogram(), Log2Histogram()]
        else:
            self._lat_recent = [Log2Histogram(), self._lat_recent[0]]
            self._ext_recent = [Log2Histogram(), self._ext_recent[0]]
        self._recent_t0 = self._now()

    def record_rejected(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_expired(self, n: int = 1) -> None:
        with self._lock:
            self.expired += n

    def record_errors(self, n: int = 1) -> None:
        with self._lock:
            self.errors += n

    def record_shutdown(self, n: int = 1) -> None:
        with self._lock:
            self.shutdown += n

    def record_retry(self) -> None:
        with self._lock:
            self.retries += 1

    def record_oom_degrade(self, requeued: int) -> None:
        with self._lock:
            self.oom_degrades += 1
            self.requeued += requeued

    def record_watchdog_trip(self) -> None:
        with self._lock:
            self.watchdog_trips += 1

    def record_requeue_shed(self, n: int = 1) -> None:
        with self._lock:
            self.requeue_shed += n

    def record_mesh_fault(self) -> None:
        with self._lock:
            self.mesh_faults += 1

    def record_mesh_degrade(self, requeued: int = 0) -> None:
        with self._lock:
            self.mesh_degrades += 1
            self.requeued += requeued

    def record_audit(self, lag_ms: float, *, failed: bool = False) -> None:
        with self._lock:
            self.audits_run += 1
            if failed:
                self.audit_failures += 1
            self._audit_lag_hist.add(lag_ms)

    def record_audit_error(self) -> None:
        with self._lock:
            self.audit_errors += 1

    def record_audit_dropped(self) -> None:
        with self._lock:
            self.audit_dropped += 1

    def record_quarantine(self) -> None:
        with self._lock:
            self.quarantines += 1

    def record_cache_hit(self, latency_ms: float, *,
                         landmark: bool = False) -> None:
        """One query resolved WITHOUT a traversal. Counts toward
        ``completed`` (it is a served query) but its latency lands in
        the hit histogram, not the batch-latency one, so ``p50_ms``
        keeps meaning the traversal path. Landmark hits are already
        counted by ``record_landmark`` — only plain cache hits bump
        ``cache_hits`` here."""
        with self._lock:
            self.completed += 1
            if not landmark:
                self.cache_hits += 1
            self._hit_hist.add(latency_ms)

    def record_follower_completed(self) -> None:
        """A single-flight follower resolved ok off its leader's result
        — a served query that never occupied a lane, so no batch counter
        (or latency histogram) ever sees it."""
        with self._lock:
            self.completed += 1

    def record_cache_miss(self) -> None:
        with self._lock:
            self.cache_misses += 1

    def record_cache_eviction(self, n: int = 1) -> None:
        with self._lock:
            self.cache_evictions += n

    def set_cache_bytes(self, nbytes: int) -> None:
        with self._lock:
            self.cache_bytes = int(nbytes)

    def record_cache_quarantine(self) -> None:
        with self._lock:
            self.cache_quarantines += 1

    def record_single_flight(self, n: int = 1) -> None:
        with self._lock:
            self.single_flight_collapses += n

    def record_landmark(self, *, exact: bool,
                        informative: bool = True) -> None:
        """One landmark consult: ``exact`` answered the query;
        otherwise the bracket existed but did not meet (``bounded``) or
        no landmark was informative at all (``fallback``) — both fall
        back to traversal."""
        with self._lock:
            if exact:
                self.landmark_exact += 1
            elif informative:
                self.landmark_bounded += 1
            else:
                self.landmark_fallback += 1

    def _round(self, v: float | None) -> float | None:
        return None if v is None else round(v, 3)

    def snapshot(self, *, queue_depth: int | None = None,
                 lanes: int | None = None, mark_interval: bool = False,
                 extra: dict | None = None) -> dict:
        """One /statsz observation. ``interval_qps`` covers the window
        since the last ``mark_interval=True`` snapshot; only the ONE
        periodic emitter (statsz_line) passes that flag — ad-hoc
        observers (BfsService.statsz, the bench) must not reset the
        periodic line's window. ``qps`` is lifetime."""
        with self._lock:
            now = self._now()
            uptime = max(now - self._t0, 1e-9)
            interval = max(now - self._last_snap_t, 1e-9)
            interval_done = self.completed - self._last_snap_completed
            if mark_interval:
                self._last_snap_t = now
                self._last_snap_completed = self.completed
            # Percentile keys read the recent window pair (a long-idle
            # server's percentiles age back to None rather than echoing
            # an hour-old cold batch); the all-time histograms stay the
            # exported/mergeable record.
            self._rotate_recent()
            lat = Log2Histogram().merge(
                self._lat_recent[0]).merge(self._lat_recent[1])
            ext = Log2Histogram().merge(
                self._ext_recent[0]).merge(self._ext_recent[1])
            out = {
                "uptime_s": round(uptime, 3),
                "completed": self.completed,
                "qps": round(self.completed / uptime, 2),
                "interval_qps": round(interval_done / interval, 2),
                "p50_ms": self._round(lat.percentile(50)),
                "p99_ms": self._round(lat.percentile(99)),
                "fill_ratio": round(
                    self.lanes_used / self.lanes_offered, 4
                ) if self.lanes_offered else 0.0,
                "padded_lanes_total": self.padded_lanes_total,
                # Routing histogram (width ladder): how many batches each
                # dispatched width served. JSON keys must be strings.
                "routing": {
                    str(wd): n
                    for wd, n in sorted(self.batches_by_width.items())
                },
                "extract_p50_ms": self._round(ext.percentile(50)),
                "extract_ms_total": round(self.extract_ms_total, 3),
                "batches": self.batches,
                "rejected": self.rejected,
                "expired": self.expired,
                "errors": self.errors,
                "shutdown": self.shutdown,
                "retries": self.retries,
                "oom_degrades": self.oom_degrades,
                "requeued": self.requeued,
                "watchdog_trips": self.watchdog_trips,
                "requeue_shed": self.requeue_shed,
                "mesh_faults": self.mesh_faults,
                "mesh_degrades": self.mesh_degrades,
                "audits_run": self.audits_run,
                "audit_failures": self.audit_failures,
                "audit_errors": self.audit_errors,
                "audit_dropped": self.audit_dropped,
                "audit_p50_lag_ms": self._round(
                    self._audit_lag_hist.percentile(50)
                ),
                "quarantines": self.quarantines,
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "cache_evictions": self.cache_evictions,
                "cache_bytes": self.cache_bytes,
                "cache_quarantines": self.cache_quarantines,
                "single_flight_collapses": self.single_flight_collapses,
                "landmark_exact": self.landmark_exact,
                "landmark_bounded": self.landmark_bounded,
                "landmark_fallback": self.landmark_fallback,
                # Hit-path latency is all-time (hits are microsecond
                # NumPy work — there is no cold-batch-haunts-p99 problem
                # to age out), keeping the split p50 pair comparable.
                "hit_p50_ms": self._round(self._hit_hist.percentile(50)),
            }
        if queue_depth is not None:
            out["queue_depth"] = queue_depth
        if lanes is not None:
            out["lanes"] = lanes
        if extra:
            # Service-level observations riding the line (breaker state,
            # drain flag, injected-fault audit — BfsService.statsz_extras).
            out.update(extra)
        return out

    def histograms(self) -> dict:
        """CONSISTENT COPIES of the mergeable all-time distributions,
        taken under the lock — a batch completing mid-render must not
        yield an exposition whose +Inf bucket disagrees with its _count
        (the Prometheus histogram invariant scrapers difference on).
        Copies are also safe to hand to a merging aggregator."""
        with self._lock:
            return {
                "latency_ms": Log2Histogram().merge(self._latency_hist),
                "extract_ms": Log2Histogram().merge(self._extract_hist),
                "hit_ms": Log2Histogram().merge(self._hit_hist),
            }

    def prometheus_text(self, snapshot: dict | None = None, **kw) -> str:
        """THE ONE /metricz renderer (BfsService.metricz and the
        periodic ``--metricz-out`` writer both delegate here): pass the
        exact snapshot dict another rendering just printed (the statsz
        line) so the two outputs come from one observation and can
        never disagree; with no snapshot given, one is taken now."""
        from tpu_bfs.obs.exporters import prometheus_text

        snap = snapshot if snapshot is not None else self.snapshot(**kw)
        return prometheus_text(snap, histograms=self.histograms())

    def statsz_line(self, snapshot: dict | None = None, **kw) -> str:
        """The periodic stderr line: a stable prefix + one JSON object, so
        log scrapers can grep ``statsz`` and parse the rest. The only
        path that advances the interval-QPS window — either directly or
        via the prebuilt ``snapshot`` the periodic emitter shares with
        the /metricz rendering."""
        if snapshot is None:
            snapshot = self.snapshot(mark_interval=True, **kw)
        return "statsz " + json.dumps(snapshot)
