"""Warm-engine registry: graphs loaded once, engines built-and-warmed once.

A fresh engine build costs an ELL/tile build plus an XLA compile of the
packed level loop (~20-40 s first-compile on chip); a server cannot pay
that per query. The registry keys resident engines by
``(graph_key, engine, lanes, pull_gate, devices, exchange config,
mesh_shape)`` — every axis that changes the compiled program — warms
each build with one full-width
batch so serving dispatches never see the compile, and bounds residency
with an LRU (each resident engine holds its packed tables in HBM, so
"cache them all" is not an option).

``enable_compile_cache`` (utils/compile_cache.py) is armed at registry
construction: the warm-up run populates the persistent XLA cache, so
even an evicted-and-rebuilt engine (or a restarted server) pays a disk
hit, not a recompile.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict

import numpy as np

from tpu_bfs import faults as _faults
from tpu_bfs import obs as _obs
from tpu_bfs.utils.compile_cache import enable_compile_cache

ENGINE_KINDS = ("wide", "hybrid", "packed", "dist2d")

# The distributed hybrid's dense MXU kernel runs on every shard, so its
# serving widths come in whole 4096-lane steps (dist_msbfs_hybrid.LANES;
# the single-chip hybrid shares the quantum). Kept as a literal here so
# spec validation never imports the engine modules (they stay lazy).
HYBRID_LANE_QUANTUM = 4096

# Per-engine legal exchange families ("" = the engine's own default).
# Mesh-only: single-chip engines run no exchange at all.
ENGINE_EXCHANGES = {
    "wide": ("", "dense", "sparse"),
    "hybrid": ("", "dense", "sparse", "sliced"),
    "dist2d": ("", "ring", "allreduce", "sparse"),
    "packed": ("",),
}

# Kind-specific mesh exchange families (ISSUE 20): a kind whose
# distributed engine is NOT the engine family's own loop overrides the
# family's exchange list. sssp on a mesh runs the (min, +) delta-stepping
# engine (parallel/dist_sssp.py) whose value exchanges are
# ring/allreduce/sparse — not the wide family's OR row gathers.
KIND_EXCHANGES = {
    "sssp": ("", "ring", "allreduce", "sparse"),
}

# Serving engines default to 8 planes (254-level depth cap) where the
# one-shot CLI defaults to 5 (32 levels): a server answers arbitrary
# sources on a long-lived process, and one high-eccentricity query
# truncating a whole batch into error responses costs far more than the
# 3 extra planes' HBM.
DEFAULT_PLANES = 8


def mesh_shape_2d(devices: int, mesh_shape=()) -> tuple[int, int]:
    """The (rows, cols) factorization the 2D engine serves on: an
    explicit ``mesh_shape`` wins; otherwise the most-square factorization
    of ``devices`` (Buluç & Madduri's 2D decomposition wants R ~ C — both
    per-chip collective terms then shrink as O(vp/sqrt(P)))."""
    if mesh_shape:
        r, c = int(mesh_shape[0]), int(mesh_shape[1])
        if r < 1 or c < 1 or r * c != devices:
            raise ValueError(
                f"mesh_shape {r}x{c} does not cover {devices} devices"
            )
        return r, c
    r = int(np.sqrt(devices))
    while devices % r:
        r -= 1
    return r, devices // r


@dataclasses.dataclass(frozen=True)
class EngineSpec:
    """One resident engine's identity — everything that changes the
    compiled program or its tables. The mesh axes (``devices``,
    ``mesh_shape``) and the exchange configuration (``exchange``,
    ``wire_pack``, ``delta_bits``, ``sieve``, ``predict``) are key fields
    too: each changes the compiled collective program, so two configs can
    never alias one resident engine (or one AOT artifact — utils/aot.py
    keys off the same axes)."""

    graph_key: str
    engine: str = "wide"
    lanes: int = 512
    planes: int = DEFAULT_PLANES
    pull_gate: bool = False
    #: ISSUE 16 expansion tier: "xla" (the fori-loop form XLA fuses) or
    #: "pallas" (the fused gather-combine kernel, ops/ell_expand.py).
    #: A key field — the tiers compile different programs over different
    #: table sets — carried by utils/aot.program_key only when
    #: non-default, so existing stores stay adoptable.
    expand_impl: str = "xla"
    devices: int = 1
    #: Query kind this residency serves (ISSUE 14): "bfs" (the base
    #: engines themselves) or a tpu_bfs/workloads adapter over them
    #: (sssp/cc/khop/p2p). A key field — per-kind engines hold
    #: different device state (SSSP's weighted tiles, CC's cached
    #: index) and answer through different programs, so kinds never
    #: alias one resident engine; utils/aot.program_key carries the
    #: axis the same way (only when non-default, so existing stores
    #: stay adoptable).
    kind: str = "bfs"
    #: exchange family ("" = engine default): wide/hybrid row gathers
    #: (dense/sparse; hybrid also 'sliced'), dist2d row exchange
    #: (ring/allreduce/sparse). Mesh engines only.
    exchange: str = ""
    #: ISSUE 5 bit-packed wire format (mesh engines; validated no-op on
    #: the packed MS engines whose lane words already carry 1 bit).
    wire_pack: bool = False
    #: ISSUE 7 planner pieces (sparse exchanges only; sieve/predict are
    #: the 1D/2D planner's — the MS row gathers take delta_bits alone).
    delta_bits: tuple = ()
    sieve: bool = False
    predict: bool = False
    #: explicit (rows, cols) for the dist2d engine; () = most-square.
    mesh_shape: tuple = ()
    #: ISSUE 19 dynamic graphs: the served graph's version, bumped on
    #: every applied mutation batch. A KEY field — post-flip queries
    #: must never alias a pre-flip residency by key — but NOT a compiled
    #: axis: the registry REKEYS the resident engine across a flip
    #: (:meth:`EngineRegistry.rekey_generation`) instead of rebuilding,
    #: because only the overlay table VALUES change; utils/aot.program_key
    #: omits it for the same reason.
    graph_generation: int = 0
    #: ISSUE 19 delta-overlay capacity ``(rows, kcap)``; () = static
    #: graph. A key AND compiled axis: the overlay engine's core carries
    #: the delta fold over fixed-shape tables sized by this.
    overlay: tuple = ()
    #: ISSUE 12 level-checkpointed resume cadence K (dist2d only; 0 =
    #: off): the serving loop runs K levels per chunk and snapshots its
    #: carry at each boundary (tpu_bfs/resilience/resume), so a
    #: mid-query mesh fault resumes from the last intact level on the
    #: degraded mesh. NOT part of the compiled program (the chunks
    #: re-drive one compiled loop with new level bounds), but a key
    #: field so a resuming and a non-resuming service never alias one
    #: resident engine; utils/aot.program_key deliberately omits it, so
    #: both adopt the same artifacts.
    resume_levels: int = 0

    def __post_init__(self):
        # Hashability + registry-key stability: list-valued knobs arrive
        # from argparse/env parsing; freeze them.
        object.__setattr__(self, "delta_bits", tuple(self.delta_bits))
        object.__setattr__(self, "mesh_shape", tuple(self.mesh_shape))
        object.__setattr__(
            self, "overlay", tuple(int(x) for x in self.overlay)
        )

    def validate(self) -> None:
        if self.engine not in ENGINE_KINDS:
            raise ValueError(
                f"engine must be one of {ENGINE_KINDS}, got {self.engine!r}"
            )
        if self.lanes % 32 or self.lanes < 32:
            raise ValueError(
                f"lanes must be a multiple of 32 >= 32, got {self.lanes}"
            )
        if self.engine == "hybrid" and self.lanes % HYBRID_LANE_QUANTUM:
            raise ValueError(
                f"the hybrid engine's dense kernel takes whole "
                f"{HYBRID_LANE_QUANTUM}-lane steps, got {self.lanes}"
            )
        if self.engine == "packed" and self.pull_gate:
            raise ValueError(
                "pull_gate applies to the wide/hybrid engines (the packed "
                "engine keeps no settled-mask state)"
            )
        if self.engine == "packed" and self.devices > 1:
            raise ValueError("the packed engine is single-device")
        if self.expand_impl not in ("xla", "pallas"):
            raise ValueError(
                "expand_impl must be one of ('xla', 'pallas'), got "
                f"{self.expand_impl!r}"
            )
        if self.expand_impl != "xla" and self.engine in ("packed", "dist2d"):
            raise ValueError(
                "expand_impl='pallas' fuses the bucketed-ELL pull "
                "expansion of the wide/hybrid engines; the packed and "
                "dist2d engines run no ELL pull loop to lower"
            )
        if self.engine == "dist2d" and self.devices < 2:
            raise ValueError(
                "the dist2d engine is the 2D-partition mesh path; "
                "use devices >= 2 (single-chip serving has no exchange "
                "to partition)"
            )
        if self.engine == "wide" and self.devices > 1 and self.pull_gate:
            # Mirrors the CLI's rejection: the distributed wide engine has
            # no gate machinery — silently serving ungated would lie.
            raise ValueError(
                "pull_gate on a mesh runs through the distributed hybrid "
                "engine; use engine='hybrid' with devices > 1"
            )
        if self.engine == "dist2d" and self.pull_gate:
            raise ValueError(
                "pull_gate gates the packed MS engines' pull expansion; "
                "the 2D engine has no settled-mask machinery"
            )
        if self.devices == 1 and (
            self.exchange or self.wire_pack or self.delta_bits
            or self.sieve or self.predict
        ):
            raise ValueError(
                "exchange/wire_pack/delta_bits/sieve/predict shape the "
                "MESH exchanges; single-chip engines (devices=1) run none"
            )
        legal_exchanges = (
            KIND_EXCHANGES.get(self.kind, ENGINE_EXCHANGES[self.engine])
            if self.devices > 1 else ENGINE_EXCHANGES[self.engine]
        )
        if self.exchange not in legal_exchanges:
            raise ValueError(
                f"exchange {self.exchange!r} is not one of "
                f"{legal_exchanges} for engine {self.engine!r}"
                + (f" serving kind {self.kind!r}"
                   if self.kind in KIND_EXCHANGES and self.devices > 1
                   else "")
            )
        if self.delta_bits and self.exchange != "sparse":
            raise ValueError(
                "delta_bits compresses the SPARSE exchanges' id streams; "
                f"set exchange='sparse' (got {self.exchange!r})"
            )
        if (self.sieve or self.predict) and not (
            (self.engine == "dist2d" and self.exchange == "sparse")
            or (self.kind == "sssp" and self.devices > 1
                and self.exchange == "sparse" and not self.sieve)
        ):
            raise ValueError(
                "sieve/predict are the exchange planner's pieces; on the "
                "serve tier they apply to engine='dist2d' with "
                "exchange='sparse', plus predict (alone — min carries no "
                "sieve residue to compact) on the distributed sssp "
                "engine's sparse exchange (the MS row gathers take "
                "delta_bits only)"
            )
        if self.mesh_shape:
            if self.engine != "dist2d" and not (
                self.kind == "sssp" and self.devices > 1
            ):
                raise ValueError(
                    "mesh_shape picks a 2D (rows, cols) partition — the "
                    "dist2d engine's, or the distributed sssp engine's "
                    f"(kind='sssp', devices > 1); engine {self.engine!r} "
                    "runs a 1D mesh"
                )
            mesh_shape_2d(self.devices, self.mesh_shape)  # raises on mismatch
            if self.kind == "sssp" and self.exchange not in ("", "allreduce"):
                raise ValueError(
                    "the 2D distributed sssp engine exchanges "
                    "hierarchically (pmin over both axes) — exchange must "
                    f"be '' or 'allreduce', got {self.exchange!r}"
                )
        if self.resume_levels < 0:
            raise ValueError(
                f"resume_levels must be >= 0, got {self.resume_levels}"
            )
        if self.resume_levels and self.engine != "dist2d":
            raise ValueError(
                "resume_levels drives the dist2d serve adapter's chunked "
                "level loop (one single-source loop per unique lane); the "
                "packed MS engines answer a whole batch in one fused loop "
                "with no per-query carry to snapshot — a mesh fault there "
                "re-traverses the batch on the degraded mesh instead"
            )
        if self.graph_generation < 0:
            raise ValueError(
                f"graph_generation must be >= 0, got {self.graph_generation}"
            )
        if self.overlay:
            if len(self.overlay) != 2 or min(self.overlay) < 1:
                raise ValueError(
                    f"overlay must be (rows, kcap) with both >= 1, got "
                    f"{self.overlay}"
                )
            if self.engine != "wide" or self.devices > 1:
                raise ValueError(
                    "the delta overlay rides the single-chip wide "
                    "substrate (ISSUE 19); the mesh generalization "
                    "follows the partitioned tiles"
                )
            if self.pull_gate:
                raise ValueError(
                    "overlay does not compose with pull_gate (the gate "
                    "skips settled BASE rows; overlay edges would escape "
                    "it untraversed)"
                )
            if self.kind == "p2p":
                raise ValueError(
                    "kind 'p2p' is excluded from dynamic serving: its "
                    "path reconstruction scans the BUILD-TIME edge "
                    "tables, so a post-mutation path could silently "
                    "traverse removed edges"
                )
        if self.kind != "bfs":
            from tpu_bfs.workloads import KIND_ENGINES, KINDS

            if self.kind not in KINDS:
                raise ValueError(
                    f"kind must be one of {KINDS}, got {self.kind!r}"
                )
            if self.engine not in KIND_ENGINES[self.kind]:
                raise ValueError(
                    f"kind {self.kind!r} runs on engines "
                    f"{KIND_ENGINES[self.kind]}, not {self.engine!r}"
                )
            if (self.devices > 1 and self.kind == "sssp"
                    and self.wire_pack):
                raise ValueError(
                    "wire_pack packs the OR exchanges' frontier words; "
                    "the distributed sssp engine ships int32 distance "
                    "rows (delta_bits compresses its id stream instead)"
                )
            if self.kind in ("p2p", "sssp") and self.pull_gate:
                raise ValueError(
                    f"kind {self.kind!r} does not compose with pull_gate "
                    "(p2p steps the resumable core level by level under "
                    "its own lane pairing; sssp runs min-plus tiles with "
                    "no settled-mask machinery)"
                )


class EngineRegistry:
    """LRU-bounded store of warmed engines over once-loaded graphs.

    ``aot_store`` (an ``utils.aot.ArtifactStore`` or a directory path)
    turns builds into ADOPTIONS where artifacts exist: ``_build`` still
    constructs the graph tables, but installs deserialized executables
    (``adopt_programs``) over the engine's jit entries instead of
    compiling — the ``--preheat`` path (ISSUE 9). Stale or corrupt
    artifacts fall back to JIT per program; the store's hit/fallback
    counts surface in statsz.
    """

    def __init__(self, *, capacity: int = 4, warm: bool = True, log=None,
                 aot_store=None):
        if capacity < 1:
            raise ValueError(f"registry capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._warm = warm
        self._log = log or (lambda msg: None)
        if isinstance(aot_store, str):
            from tpu_bfs.utils.aot import ArtifactStore

            aot_store = ArtifactStore(aot_store, log=self._log)
        self.aot_store = aot_store
        self._graphs: dict = {}  # guarded-by: _lock
        self._engines: OrderedDict = OrderedDict()  # guarded-by: _lock
        # One build at a time: engine builds allocate device tables, and
        # two concurrent builds of the same spec would double-build AND
        # double-allocate. RLock so get() -> _build() -> graph() nests.
        self._lock = threading.RLock()
        self.builds = 0  # guarded-by: _lock
        self.adoptions = 0  # guarded-by: _lock
        self.evictions = 0  # guarded-by: _lock
        enable_compile_cache(log=self._log)

    # --- graphs -----------------------------------------------------------

    def add_graph(self, key: str, graph) -> str:
        """Register an already-loaded Graph under ``key``."""
        with self._lock:
            self._graphs[key] = graph
        return key

    def graph(self, key: str):
        """The graph for ``key``, loading it on first use when the key is
        a CLI graph spec (path / rmat:... / random:...)."""
        with self._lock:
            g = self._graphs.get(key)
            if g is None:
                from tpu_bfs.cli import load_graph

                t0 = time.perf_counter()
                g = load_graph(key)
                self._graphs[key] = g
                self._log(
                    f"graph {key!r} loaded: V={g.num_vertices} "
                    f"E={g.num_edges} in {time.perf_counter() - t0:.1f}s"
                )
            return g

    # --- engines ----------------------------------------------------------

    def get(self, spec: EngineSpec):
        """The warmed engine for ``spec``, building it on first use and
        evicting least-recently-served engines over ``capacity``."""
        spec.validate()
        with self._lock:
            eng = self._engines.get(spec)
            if eng is not None:
                self._engines.move_to_end(spec)
                return eng
            eng = self._build(spec)
            if self._warm:
                self._warm_up(spec, eng)
            self._engines[spec] = eng
            while len(self._engines) > self.capacity:
                old_spec, _ = self._engines.popitem(last=False)
                self.evictions += 1
                self._log(f"evicted engine {old_spec}")
            return eng

    def _build(self, spec: EngineSpec):  # requires-lock: _lock
        rec = _obs.ACTIVE
        store = self.aot_store
        # Span naming is honest about what the build will cost: a spec
        # whose core artifact probes valid becomes an engine_adopt span
        # (table construction + executable install, no compile); only a
        # true from-scratch build emits engine_build — the span name the
        # preheat smoke asserts is ABSENT from a preheated cold start.
        adopting = store is not None and store.probe(spec)
        span = "engine_adopt" if adopting else "engine_build"
        if rec is not None:
            # Registry lifecycle span: builds are the 30-second events a
            # trace of a cold start is mostly made of.
            rec.begin(span, f"w{spec.lanes}", cat="serve.registry",
                      engine=spec.engine, width=spec.lanes,
                      planes=spec.planes, devices=spec.devices)
        adopted: list = []
        try:
            eng = self._build_inner(spec)
            if store is not None:
                from tpu_bfs.utils.aot import adopt_engine_programs

                adopted = adopt_engine_programs(
                    eng, spec, store, log=self._log
                )
                if adopted:
                    with self._lock:
                        self.adoptions += 1
                elif adopting:
                    # The probe said adoptable but nothing installed
                    # (payload undeserializable here, or a concurrent
                    # quarantine): the engine_adopt span would otherwise
                    # read as a phantom no-compile — flag it loudly.
                    self._log(
                        f"aot adoption of {spec} installed nothing; "
                        f"this build pays the full JIT path"
                    )
                    if rec is not None:
                        rec.event("aot_adopt_failed", cat="serve.registry",
                                  width=spec.lanes, engine=spec.engine)
        except Exception as exc:
            if rec is not None:
                rec.end(span, f"w{spec.lanes}",
                        cat="serve.registry", width=spec.lanes,
                        error=f"{type(exc).__name__}: {str(exc)[:120]}")
            raise
        if rec is not None:
            rec.end(span, f"w{spec.lanes}", cat="serve.registry",
                    width=spec.lanes, adopted=len(adopted))
        return eng

    def _build_inner(self, spec: EngineSpec):  # requires-lock: _lock
        if _faults.ACTIVE is not None:
            # Chaos-harness injection site: a transient raised here runs
            # the service's engine-build retry; an OOM runs the width
            # degrade — exactly like a real build failure.
            _faults.ACTIVE.hit("engine_build", lanes=spec.lanes)
        g = self.graph(spec.graph_key)
        t0 = time.perf_counter()
        if spec.kind == "sssp":
            # SSSP builds its own weighted substrate (no base BFS engine
            # to wrap): the delta-stepping tiles + weight planes.
            from tpu_bfs.workloads import build_workload_engine

            eng = build_workload_engine("sssp", None, g, spec)
            self.builds += 1
            self._log(
                f"engine built {spec} in {time.perf_counter() - t0:.1f}s"
            )
            return eng
        if spec.engine == "dist2d":
            from tpu_bfs.parallel.dist_bfs2d import (
                Dist2DServeEngine,
                make_mesh_2d,
            )

            r, c = mesh_shape_2d(spec.devices, spec.mesh_shape)
            eng = Dist2DServeEngine(
                g, make_mesh_2d(r, c), lanes=spec.lanes,
                exchange=spec.exchange or "ring",
                wire_pack=spec.wire_pack, delta_bits=spec.delta_bits,
                sieve=spec.sieve, predict=spec.predict,
                resume_levels=spec.resume_levels,
            )
        elif spec.devices > 1:
            from tpu_bfs.parallel.dist_bfs import make_mesh

            mesh = make_mesh(spec.devices)
            if spec.engine == "wide":
                from tpu_bfs.parallel.dist_msbfs_wide import DistWideMsBfsEngine

                eng = DistWideMsBfsEngine(
                    g, mesh, num_planes=spec.planes, lanes=spec.lanes,
                    exchange=spec.exchange or "dense",
                    wire_pack=spec.wire_pack, delta_bits=spec.delta_bits,
                    expand_impl=spec.expand_impl,
                )
            else:
                from tpu_bfs.parallel.dist_msbfs_hybrid import (
                    DistHybridMsBfsEngine,
                )

                eng = DistHybridMsBfsEngine(
                    g, mesh, num_planes=spec.planes, lanes=spec.lanes,
                    pull_gate=spec.pull_gate,
                    exchange=spec.exchange or "dense",
                    wire_pack=spec.wire_pack, delta_bits=spec.delta_bits,
                    expand_impl=spec.expand_impl,
                )
        elif spec.engine == "packed":
            from tpu_bfs.algorithms.msbfs_packed import PackedMsBfsEngine

            eng = PackedMsBfsEngine(g, lanes=spec.lanes)
        elif spec.engine == "hybrid":
            from tpu_bfs.algorithms.msbfs_hybrid import HybridMsBfsEngine

            eng = HybridMsBfsEngine(
                g, lanes=spec.lanes, num_planes=spec.planes,
                pull_gate=spec.pull_gate, expand_impl=spec.expand_impl,
            )
        else:
            from tpu_bfs.algorithms.msbfs_wide import WidePackedMsBfsEngine

            eng = WidePackedMsBfsEngine(
                g, lanes=spec.lanes, num_planes=spec.planes,
                pull_gate=spec.pull_gate, expand_impl=spec.expand_impl,
                overlay=spec.overlay,
            )
        if spec.kind != "bfs":
            # Workload adapter over the base engine (ISSUE 14): khop/cc/
            # p2p reuse the packed substrate's compiled programs behind
            # their kind's dispatch/fetch semantics.
            from tpu_bfs.workloads import build_workload_engine

            eng = build_workload_engine(spec.kind, eng, g, spec)
        self.builds += 1
        self._log(f"engine built {spec} in {time.perf_counter() - t0:.1f}s")
        return eng

    def _warm_up(self, spec: EngineSpec, eng) -> None:
        """One full-width batch so the serving shape is compiled (and the
        persistent XLA cache populated) before the first real dispatch.
        The serving executor always pads batches to exactly ``lanes``
        sources, so this warm run compiles THE shape every later dispatch
        reuses. Vertex 0 always exists; its answer is discarded."""
        t0 = time.perf_counter()
        with _obs.maybe_span("engine_warm", f"w{spec.lanes}",
                             cat="serve.registry", width=spec.lanes,
                             engine=spec.engine):
            eng.run(np.zeros(eng.lanes, dtype=np.int64), time_it=False)
            # Residency warm-up hook (ISSUE 15 satellite / ROADMAP 3b):
            # engines with per-residency caches beyond the compiled
            # programs build them HERE, inside the warm span, so the
            # first real query never pays a cold path — the p2p adapter
            # builds its cached parent scanner (without it, every first
            # path reconstruction paid the O(E) host scatter-min).
            warm = getattr(eng, "warm_residency", None)
            if warm is not None:
                warm()
        self._log(f"engine warmed {spec} in {time.perf_counter() - t0:.1f}s")

    def rekey_generation(self, graph_key: str, generation: int) -> int:
        """Move every resident engine of ``graph_key`` onto the new
        ``graph_generation`` key WITHOUT a rebuild (ISSUE 19): a
        mutation flip swaps overlay table values under the same compiled
        program, so the residency survives — only its registry identity
        moves, atomically under the lock, preserving LRU order.
        In-flight batches keep their pinned engine reference; the next
        ``get`` under the new-generation spec hits the moved residency
        instead of paying a build. Returns how many residencies moved."""
        moved = 0
        with self._lock:
            items = list(self._engines.items())
            self._engines.clear()
            for spec, eng in items:
                if (spec.graph_key == graph_key
                        and spec.graph_generation != generation):
                    spec = dataclasses.replace(
                        spec, graph_generation=generation
                    )
                    moved += 1
                self._engines[spec] = eng
            return moved

    def drop_graph_engines(self, graph_key: str) -> int:
        """Evict every resident engine of ``graph_key`` (the compaction
        path: a NEW base generation's tables invalidate every compiled
        residency — unlike a flip, the ELL itself changed). Returns the
        eviction count."""
        dropped = 0
        with self._lock:
            for spec in [s for s in self._engines
                         if s.graph_key == graph_key]:
                self._engines.pop(spec)
                self.evictions += 1
                dropped += 1
                self._log(f"evicted engine {spec} (compaction)")
            return dropped

    def evict(self, spec: EngineSpec) -> bool:
        """Drop ``spec``'s engine (if resident) so its device tables can
        free. The OOM-degrade ladder calls this on the JUST-OOM'd width
        BEFORE building the narrower engine — the rebuild must not have
        to fit next to the dying engine's allocations (the same lesson
        bench.py's adaptive-shed dance encodes)."""
        with self._lock:
            if self._engines.pop(spec, None) is None:
                return False
            self.evictions += 1
            self._log(f"evicted engine {spec} (explicit)")
            return True

    def resident(self) -> list | None:
        """Resident specs, least-recently-served first (for /statsz), or
        None when a build currently holds the registry lock — the
        observability read must never block behind a minutes-long
        compile (it exists to watch exactly those incidents)."""
        if not self._lock.acquire(timeout=0.05):
            return None
        try:
            return list(self._engines)
        finally:
            self._lock.release()

    def resident_engines(self) -> list:
        """``(spec, engine)`` pairs, same non-blocking discipline as
        :meth:`resident` (empty when a build holds the lock) — the trace
        exporter walks these for ``last_run_trace`` level tracks."""
        if not self._lock.acquire(timeout=0.05):
            return []
        try:
            return list(self._engines.items())
        finally:
            self._lock.release()

    def export_resident(self, store=None) -> dict:
        """Export every resident engine's serving programs into
        ``store`` (default: the registry's own) — the ``--export-aot``
        path: a warmed server populates the artifact store a successor
        preheats from. Returns ``{spec: [exported names]}``. Builds are
        serialized by the registry lock as usual; the export itself
        holds no registry state."""
        from tpu_bfs.utils.aot import ArtifactStore, export_engine_programs

        if isinstance(store, str):
            store = ArtifactStore(store, log=self._log)
        store = store or self.aot_store
        if store is None:
            raise ValueError(
                "export_resident needs an artifact store (construct the "
                "registry with aot_store=... or pass one here)"
            )
        out = {}
        for spec, eng in self.resident_engines():
            names = export_engine_programs(eng, spec, store, log=self._log)
            self._log(
                f"aot export {spec.engine}/w{spec.lanes}: "
                f"{len(names)} programs -> {store.root}"
            )
            out[spec] = names
        return out
