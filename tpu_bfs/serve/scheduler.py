"""Admission queue + lane-batch coalescing for the BFS query server.

Single-source queries arrive one at a time; the packed engines answer up
to ``lanes`` of them in one device dispatch. The scheduler's whole job is
bridging that impedance:

- a BOUNDED queue (``queue_cap``): at overload, new queries are shed with
  an explicit REJECTED result instead of growing an unbounded backlog —
  a server that queues forever converts overload into timeout storms;
- COALESCING: each dispatch drains up to ``max_n`` pending queries into
  one batch, lingering up to ``linger_s`` for stragglers when the batch
  is not yet full (latency <-> fill trade, the --linger-ms knob);
- DEADLINES: a query whose deadline passes while queued resolves with
  DEADLINE_EXCEEDED at batch-forming time, and ``expired()`` is checked
  AGAIN at dispatch (serve/executor.dispatch_batch) — a query that
  survived an OOM requeue, a breaker reroute, or a mesh-degrade
  re-admission must not burn chip time after its client stopped
  waiting. Deadlines bound time BEFORE dispatch, not device execution —
  once dispatched, a batch runs to completion and late results are
  still delivered (killing a running batch would punish its 8000
  batch-mates for one impatient client).

Every admitted query is resolved exactly once — completion, expiry,
rejection, error, or shutdown — never silently dropped (the acceptance
bar: "never hangs, never silent drops").
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque

import numpy as np

from tpu_bfs import obs as _obs

STATUS_OK = "ok"
STATUS_REJECTED = "rejected"  # shed at admission (queue full / closed)
STATUS_EXPIRED = "deadline_exceeded"
STATUS_ERROR = "error"
STATUS_SHUTDOWN = "shutdown"  # still queued when the service closed


@dataclasses.dataclass
class QueryResult:
    """One query's terminal outcome (exactly one per admitted query)."""

    id: object
    source: int
    status: str
    kind: str = "bfs"  # query kind (ISSUE 14: bfs|sssp|cc|khop|p2p)
    distances: np.ndarray | None = None  # [V] int32, INF_DIST unreached
    levels: int | None = None  # this source's eccentricity (max finite dist)
    reached: int | None = None
    # Kind-specific response fields (ISSUE 14): e.g. p2p's target/
    # distance/path, cc's component/size/count, khop's k. Merged into
    # the JSONL response verbatim.
    extras: dict | None = None
    latency_ms: float | None = None  # submit -> resolve (extraction included)
    batch_lanes: int | None = None  # real queries in the serving batch
    dispatched_lanes: int | None = None  # width the batch was routed to
    devices: int | None = None  # mesh span of the serving engine
    edges: int | None = None  # input edges this query's traversal covered
    device_ms: float | None = None  # its batch's dispatch -> fetch time
    wire_bytes: float | None = None  # modeled exchange bytes, per-query share
    error: str | None = None

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK

    @property
    def gteps(self) -> float | None:
        """Per-query GTEPS under the batch time share (the repo's
        harmonic-mean convention: the batch's device time divides evenly
        over its real queries, so the MEAN of a batch's per-query
        figures equals the batch's aggregate rate). None when the
        serving engine exposes no edge counts or the batch wasn't
        timed."""
        if not self.edges or not self.device_ms or not self.batch_lanes:
            return None
        share_s = self.device_ms / 1e3 / self.batch_lanes
        return self.edges / share_s / 1e9


_QUERY_SEQ = itertools.count(1)


class PendingQuery:
    """A submitted query: a one-shot future the scheduler resolves.

    ``resolve`` is idempotent (first writer wins) so racy paths — e.g. a
    shutdown drain against an in-flight batch completing — can both try
    without double-delivery. Callbacks added after resolution fire
    immediately on the caller's thread.

    ``want_distances=False`` marks a metadata-only query (levels/reached
    only): with the engines' on-device summaries, such a query never
    pulls its distance row off the device at all.

    ``requeues``/``attempt_widths`` record every OOM-driven re-admission
    (the service's degrade ladder): the requeue budget reads the count,
    and a query shed at the budget carries its attempt history in the
    error so the failure names every width that was tried."""

    __slots__ = ("id", "source", "kind", "k", "target", "deadline",
                 "t_submit", "want_distances",
                 "requeues", "attempt_widths", "obs_batch",
                 "_event", "_lock", "_result", "_callbacks")

    def __init__(self, source: int, *, id=None, deadline: float | None = None,
                 now: float | None = None, want_distances: bool = True,
                 kind: str = "bfs", k: int | None = None,
                 target: int | None = None):
        self.id = next(_QUERY_SEQ) if id is None else id
        self.source = int(source)
        # Query kind (ISSUE 14) + its per-kind parameters: khop's hop
        # bound k, p2p's target endpoint. Immutable after admission —
        # the batch key below coalesces only compatible queries.
        self.kind = kind
        self.k = k if k is None else int(k)
        self.target = target if target is None else int(target)
        self.deadline = deadline  # absolute time.monotonic() value, or None
        self.t_submit = time.monotonic() if now is None else now
        self.want_distances = bool(want_distances)
        self.requeues = 0  # OOM-driven re-admissions so far
        self.attempt_widths: list = []  # width each failed attempt ran at
        self.obs_batch = None  # serving batch id (telemetry; armed only)
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result: QueryResult | None = None  # guarded-by: _lock
        self._callbacks: list = []  # guarded-by: _lock
        rec = _obs.ACTIVE
        if rec is not None:
            # The query's span opens at ADMISSION; resolve() closes it
            # with the terminal status, batch id, and attempt history —
            # one span chain per query id across whichever threads serve
            # it (tpu_bfs/obs).
            rec.begin("query", f"q{self.id}",  # span-outlives: resolve() closes it with the terminal status
                      cat="serve.query",
                      query=self.id, source=self.source, kind=self.kind,
                      want_distances=self.want_distances)

    @property
    def batch_key(self):
        """Coalescing compatibility class (ISSUE 14): only queries whose
        one device dispatch can answer them together share a batch —
        same kind, and for khop the same hop bound (one ``max_levels``
        per dispatch)."""
        if self.kind == "khop":
            return ("khop", self.k)
        return (self.kind,)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    def resolve(self, result: QueryResult) -> bool:
        """Deliver the terminal result; False if already resolved."""
        with self._lock:
            if self._result is not None:
                return False
            self._result = result
            callbacks, self._callbacks = self._callbacks, []
        rec = _obs.ACTIVE
        if rec is not None:
            rec.end("query", f"q{self.id}", cat="serve.query",
                    query=self.id, status=result.status,
                    latency_ms=result.latency_ms, batch=self.obs_batch,
                    dispatched_lanes=result.dispatched_lanes,
                    requeues=self.requeues,
                    attempt_widths=list(self.attempt_widths))
        self._event.set()
        for cb in callbacks:
            cb(self)
        return True

    def resolve_status(self, status: str, *, error: str | None = None) -> bool:
        return self.resolve(QueryResult(
            id=self.id, source=self.source, status=status, error=error,
            kind=self.kind,
            latency_ms=(time.monotonic() - self.t_submit) * 1e3,
        ))

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> QueryResult:
        if not self._event.wait(timeout):
            raise TimeoutError(f"query {self.id!r} still pending after {timeout}s")
        # The event wait already orders this read after resolve()'s write;
        # the lock keeps the access inside the attribute's stated
        # discipline (tpu_bfs/analysis lock lint) at zero practical cost.
        with self._lock:
            return self._result

    def add_done_callback(self, cb) -> None:
        with self._lock:
            if self._result is None:
                self._callbacks.append(cb)
                return
        cb(self)


def dedupe_key(q) -> tuple:
    """The identity class single-flight collapses on (ISSUE 18): two
    queries whose terminal payloads are interchangeable — same kind,
    source, per-kind params, and distance appetite. Deadlines and ids
    deliberately excluded: a follower rides the leader's dispatch and
    keeps its own id/latency."""
    return (q.kind, q.source, q.k, q.target, q.want_distances)


def _fanout(leader: PendingQuery, follower: PendingQuery) -> None:
    """Resolve a single-flight follower from its leader's terminal
    result: same payload (arrays shared read-only), the follower's own
    id and submit-to-now latency."""
    r = leader.result(0)
    follower.resolve(dataclasses.replace(
        r, id=follower.id,
        latency_ms=(time.monotonic() - follower.t_submit) * 1e3,
    ))


class InflightIndex:
    """Single-flight collapsing of identical in-flight queries
    (ISSUE 18): the FIRST submission of a ``dedupe_key`` becomes the
    LEADER and proceeds to admission; every concurrent duplicate becomes
    a FOLLOWER that never enters the queue — it resolves the moment the
    leader does, from a per-follower copy of the leader's result. N
    duplicate submissions occupy ONE lane instead of N, independent of
    whether the answer cache is armed.

    Thread-safe; leaders self-release on resolution (any terminal
    status, including REJECTED/ERROR — a failed leader fans its failure
    out rather than leaving followers hanging)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._leaders: dict = {}  # guarded-by: _lock

    def attach(self, q: PendingQuery) -> PendingQuery | None:
        """Register ``q`` as leader (returns None: caller admits it) or
        attach it as a follower to the in-flight leader (returns the
        leader: caller must NOT admit ``q`` — it is already wired to
        resolve)."""
        key = dedupe_key(q)
        with self._lock:
            leader = self._leaders.get(key)
            if leader is None:
                self._leaders[key] = q
        if leader is None:
            # Self-release on ANY terminal status; a later identical
            # query then leads its own dispatch (resolved results are
            # the cache's business, not the inflight index's).
            q.add_done_callback(lambda _p, k=key: self._release(k))
            return None
        leader.add_done_callback(
            lambda lead, fq=q: _fanout(lead, fq)
        )
        return leader

    def _release(self, key) -> None:
        with self._lock:
            self._leaders.pop(key, None)

    def depth(self) -> int:
        with self._lock:
            return len(self._leaders)


class AdmissionQueue:
    """Bounded FIFO of PendingQuery with batch-draining semantics.

    The queue itself never resolves queries (metrics and result policy
    stay with the service); it only admits, re-admits, and hands out
    batches. ``requeue`` bypasses the cap: those queries were already
    admitted once, and dropping them on re-admission after an OOM would
    be a silent drop."""

    def __init__(self, cap: int):
        if cap < 1:
            raise ValueError(f"queue cap must be >= 1, got {cap}")
        self.cap = cap
        self._items: deque = deque()  # guarded-by: _cond
        # Per-batch-key pending counts, maintained incrementally so the
        # kind-aware linger condition stays O(1) per wake (ISSUE 14) —
        # and so pure single-kind traffic (the common case) keeps the
        # original popleft fast path with no deque rebuild.
        self._key_counts: dict = {}  # guarded-by: _cond
        self._cond = threading.Condition()
        self._stopped = False  # guarded-by: _cond

    def _bump(self, key, d: int) -> None:  # requires-lock: _cond
        c = self._key_counts.get(key, 0) + d
        if c:
            self._key_counts[key] = c
        else:
            self._key_counts.pop(key, None)

    def offer(self, q: PendingQuery) -> bool:
        """Admit, or False when the queue is full/stopped (caller sheds)."""
        with self._cond:
            if self._stopped or len(self._items) >= self.cap:
                return False
            self._items.append(q)
            self._bump(self._key_of(q), 1)
            depth = len(self._items)
            self._cond.notify()
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event("enqueue", cat="serve.queue", query=q.id, depth=depth)
        return True

    def requeue(self, queries) -> None:
        """Re-admit (at the FRONT, preserving order) queries popped by a
        batch that could not run — an OOM'd dispatch being re-served at a
        narrower lane count must not send its queries to the back of the
        line, and must never shed them against the cap."""
        queries = list(queries)
        with self._cond:
            for q in reversed(queries):
                self._items.appendleft(q)
                self._bump(self._key_of(q), 1)
            self._cond.notify()
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event("requeue", cat="serve.queue",
                      queries=[q.id for q in queries])

    def depth(self) -> int:
        with self._cond:
            return len(self._items)

    @property
    def stopped(self) -> bool:
        with self._cond:  # one mutex hop; callers poll at batch cadence
            return self._stopped

    @staticmethod
    def _key_of(q) -> tuple:
        return getattr(q, "batch_key", ("bfs",))

    def next_batch(self, max_n: int, linger_s: float) -> list:
        """Block until work exists, then drain up to ``max_n`` queries
        COMPATIBLE with the head query's batch key (ISSUE 14: only
        same-kind — and same-k for khop — queries can share a device
        dispatch; other kinds keep their queue order for later batches).

        When fewer than ``max_n`` compatible queries are pending, lingers
        up to ``linger_s`` from the moment the batch starts forming,
        returning early the instant it fills. After ``stop()`` the
        remaining queries drain immediately (no linger, no kind filter —
        the caller only resolves them as SHUTDOWN); returns [] only when
        stopped AND empty."""
        with self._cond:
            while not self._items and not self._stopped:
                self._cond.wait()
            if self._stopped:
                taken = []
                while self._items and len(taken) < max_n:
                    q = self._items.popleft()
                    self._bump(self._key_of(q), -1)
                    taken.append(q)
                return taken
            key = self._key_of(self._items[0])
            if linger_s > 0:
                deadline = time.monotonic() + linger_s
                while (self._key_counts.get(key, 0) < max_n
                       and not self._stopped):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            if len(self._key_counts) == 1:
                # Single-kind traffic: the original O(batch) popleft path.
                n = min(max_n, len(self._items))
                taken = [self._items.popleft() for _ in range(n)]
                self._bump(key, -n)
                return taken
            taken = []
            rest: deque = deque()
            for q in self._items:
                if len(taken) < max_n and self._key_of(q) == key:
                    taken.append(q)
                else:
                    rest.append(q)
            self._items = rest
            self._bump(key, -len(taken))
            return taken

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
