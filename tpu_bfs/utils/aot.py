"""Ahead-of-time program artifacts: ``jax.export``-serialized engine
executables keyed like the serve registry (ISSUE 9).

The XLA disk cache (utils/compile_cache.py) only shortcuts the backend
compile; a fresh process still pays Python tracing, lowering, and cache
lookup per program — seconds each, for every rung of a serve width
ladder. This module serializes the LOWERED programs themselves: a warmed
server exports every program its engines expose (``export_programs()``,
the ISSUE 8 ``analysis_programs`` inventory discipline), and a successor
process deserializes and INSTALLS them over the same attributes
(``adopt_programs()``) instead of re-tracing — `tpu-bfs-serve --preheat`
reaches ready-to-serve with zero engine compiles.

Artifacts are defensive by construction:

- **keyed like the registry** — ``(graph_key, engine, lanes, planes,
  pull_gate, devices)`` plus the program name, so an artifact can never
  be installed on an engine shape it wasn't exported from;
- **environment-fingerprinted** — jax version, backend, device
  kind/count; a stale fingerprint (upgraded jax, different chip) falls
  back to JIT instead of mis-deserializing, without quarantining (the
  artifact may be valid for the fleet it was built on);
- **CRC-verified** — the checkpoint-style payload CRC32 (PR 4); a
  corrupt file is quarantined (renamed ``.corrupt``) and the load falls
  back to JIT. The ``corrupt_aot`` fault kind (tpu_bfs/faults.py,
  ``aot_load`` site) drives this arm deterministically in chaos runs.

Counter semantics: ``hits`` counts validated artifact reads,
``fallbacks`` counts loads that fell back to JIT (missing / stale /
corrupt / undeserializable), ``runtime_fallbacks`` counts adopted-call
invocations whose arguments didn't match the exported signature (e.g. a
narrower one-shot batch) and ran the original jit instead, ``exports``
counts programs written. Cross-process reuse needs a STABLE graph key
(a path or generator spec); an in-process ``graph@<id>`` key only
round-trips within one process.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import struct
import threading

import numpy as np

from tpu_bfs import faults as _faults
from tpu_bfs import obs as _obs

MAGIC = b"TBFSAOT1"
FORMAT = 1

# Program names every packed serving engine exports (the dist engines
# export their fused "dist_core" instead of "core"); "core" (or
# "dist_core") is the expensive one — the level loop — and is what
# ArtifactStore.probe keys readiness on.
CORE_NAMES = ("core", "dist_core")


class AotProgramProtocol:
    """AOT export/adopt hooks (ISSUE 9) — the serving analog of the
    ISSUE 8 ``analysis_programs`` inventory.

    Engines implement ``export_programs() -> [(name, attr, fn,
    example_args), ...]``: every compiled program the serving path
    dispatches, the engine attribute it lives on, the jitted callable,
    and ``jax.ShapeDtypeStruct`` (or concrete) example arguments —
    exactly what ``jax.export.export(fn)(*args)`` needs.
    ``adopt_programs`` installs prepared callables (deserialized
    executables wrapped by :class:`AdoptedProgram`) over those
    attributes, so a preheated process dispatches without ever tracing
    or lowering the originals."""

    _aot_adopted: tuple = ()

    def export_programs(self):
        raise NotImplementedError(
            f"{type(self).__name__} has no AOT program inventory"
        )

    def adopt_programs(self, programs: dict) -> list:
        """Install ``programs[name]`` over each inventory attribute;
        names absent from ``programs`` keep their JIT entry (partial
        stores degrade per-program, never whole-engine). Returns the
        adopted names (also kept on ``_aot_adopted`` for the analysis
        retrace sentinel and the preheat smoke)."""
        adopted = []
        for name, attr, _fn, _args in self.export_programs():
            call = programs.get(name)
            if call is not None:
                setattr(self, attr, call)
                adopted.append(name)
        self._aot_adopted = tuple(adopted)
        return adopted


class AotArtifactError(ValueError):
    """Base: an artifact that cannot serve this process."""


class CorruptAotArtifact(AotArtifactError):
    """Bad magic / torn header / payload CRC mismatch — quarantined."""


class StaleAotArtifact(AotArtifactError):
    """Environment fingerprint mismatch — fallback, NOT quarantined."""


def env_fingerprint() -> dict:
    """Everything that must match for a serialized executable to be
    safe to install here: jax version, backend, device kind and count.
    ``format`` versions the artifact layout itself."""
    import jax

    devs = jax.devices()
    return {
        "format": FORMAT,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": devs[0].device_kind if devs else "none",
        "device_count": len(devs),
    }


def program_key(spec) -> dict:
    """Canonical store key from an EngineSpec-like (dataclass, mapping)
    — the registry's own key axes, nothing else.

    The mesh-exchange axes (exchange/wire_pack/delta_bits/sieve/predict/
    mesh_shape, ISSUE 11) enter the key ONLY when non-default: each
    reshapes the compiled collective program, so two exchange configs
    must never alias one artifact — while default-config keys (and their
    digests, hence on-disk filenames) stay byte-identical to the PR 9
    layout, so existing single-chip stores remain adoptable."""
    if dataclasses.is_dataclass(spec):
        spec = dataclasses.asdict(spec)
    key = {
        "graph_key": str(spec["graph_key"]),
        "engine": str(spec["engine"]),
        "lanes": int(spec["lanes"]),
        "planes": int(spec["planes"]),
        "pull_gate": bool(spec.get("pull_gate", False)),
        "devices": int(spec.get("devices", 1)),
    }
    if spec.get("exchange"):
        key["exchange"] = str(spec["exchange"])
    if spec.get("wire_pack"):
        key["wire_pack"] = True
    if spec.get("delta_bits"):
        key["delta_bits"] = [int(b) for b in spec["delta_bits"]]
    if spec.get("sieve"):
        key["sieve"] = True
    if spec.get("predict"):
        key["predict"] = True
    if spec.get("mesh_shape"):
        key["mesh_shape"] = [int(x) for x in spec["mesh_shape"]]
    if spec.get("kind", "bfs") != "bfs":
        # The workload-kind axis (ISSUE 14): per-kind engines compile
        # different programs (SSSP's min-plus tiles, khop's clamped
        # loop shares the base core but its residency must not alias a
        # bfs rung's artifacts). Non-default only, so every existing
        # single-chip store stays adoptable byte-for-byte.
        key["kind"] = str(spec["kind"])
    if spec.get("expand_impl", "xla") != "xla":
        # The kernel-tier axis (ISSUE 16): expand_impl='pallas' compiles
        # the fused ell_expand kernel over the padded gt tables — a
        # different program than the fori tier. Non-default only, so
        # xla-tier stores keep their PR 9 digests.
        key["expand_impl"] = str(spec["expand_impl"])
    if spec.get("overlay"):
        # The dynamic-graph axis (ISSUE 19): an overlay engine's core
        # carries the delta fold over (rows, kcap) tables — a different
        # program per capacity, never aliasing the static core. The
        # GENERATION deliberately stays out: flips swap table values
        # under one compiled program, so every generation adopts the
        # same artifact.
        key["overlay"] = [int(x) for x in spec["overlay"]]
    return key


def _key_digest(key: dict) -> str:
    blob = json.dumps(key, sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()[:12]


def _crc32(payload: bytes) -> int:
    import zlib

    return zlib.crc32(payload) & 0xFFFFFFFF


class ArtifactStore:
    """One directory of fingerprinted, CRC-checked program artifacts.

    File layout: ``MAGIC + u32 header_len + header_json + payload``.
    The header carries the registry key, program name, environment
    fingerprint, and the payload CRC32; the payload is the
    ``jax.export`` serialization. Writes are atomic (tmp + rename),
    like every durable artifact in this repo (utils/checkpoint.py).
    """

    def __init__(self, root, *, log=None):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._log = log or (lambda msg: None)
        self._lock = threading.Lock()
        self.hits = 0  # guarded-by: _lock
        self.fallbacks = 0  # guarded-by: _lock
        self.runtime_fallbacks = 0  # guarded-by: _lock
        self.exports = 0  # guarded-by: _lock

    # --- bookkeeping ------------------------------------------------------

    def _bump(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def counts(self) -> dict:
        """The bench/statsz keys (BENCHMARKS.md "Cold start")."""
        with self._lock:
            return {
                "aot_hits": self.hits,
                "aot_fallbacks": self.fallbacks,
                "aot_runtime_fallbacks": self.runtime_fallbacks,
                "aot_exports": self.exports,
            }

    # --- paths ------------------------------------------------------------

    def path_for(self, key: dict, name: str) -> str:
        key = program_key(key)
        tag = (
            f"{key['engine']}-l{key['lanes']}-p{key['planes']}"
            f"{'-pg' if key['pull_gate'] else ''}-d{key['devices']}"
        )
        return os.path.join(
            self.root, f"{tag}-{name}-{_key_digest(key)}.aot"
        )

    def _quarantine(self, path: str, reason: str) -> None:
        qpath = path + ".corrupt"
        try:
            os.replace(path, qpath)
        except OSError:
            qpath = "<unmovable>"
        self._log(
            f"aot artifact corrupt ({reason}): {path} quarantined as "
            f"{qpath}; falling back to JIT"
        )

    # --- write ------------------------------------------------------------

    def put(self, key: dict, name: str, payload: bytes, *,
            donate_argnums=()) -> str:
        """Atomically write one program artifact; returns its path.

        ``donate_argnums`` records the program's buffer-donation contract
        (ISSUE 13): ``jax.export`` does not carry donation through
        deserialization, so the adopting wrapper re-applies it from the
        header — an adopted resume core aliases its carry exactly like
        the original. Written only when non-empty, so donation-free
        artifacts stay byte-identical to the PR 9 layout."""
        key = program_key(key)
        meta = {
            "key": key,
            "name": name,
            "fingerprint": env_fingerprint(),
            "payload_crc32": _crc32(payload),
        }
        if donate_argnums:
            meta["donate_argnums"] = [int(i) for i in donate_argnums]
        header = json.dumps(meta, sort_keys=True).encode()
        path = self.path_for(key, name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(MAGIC)
            f.write(struct.pack("<I", len(header)))
            f.write(header)
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._bump("exports")
        return path

    # --- read -------------------------------------------------------------

    def _read_header(self, path: str):
        """(meta, payload_offset); raises CorruptAotArtifact on any
        structural damage."""
        with open(path, "rb") as f:
            head = f.read(len(MAGIC) + 4)
            if len(head) < len(MAGIC) + 4 or head[: len(MAGIC)] != MAGIC:
                raise CorruptAotArtifact(f"bad magic in {path}")
            (hlen,) = struct.unpack("<I", head[len(MAGIC):])
            raw = f.read(hlen)
        if len(raw) < hlen:
            raise CorruptAotArtifact(f"torn header in {path}")
        try:
            meta = json.loads(raw)
        except ValueError as exc:
            raise CorruptAotArtifact(
                f"unparsable header in {path}: {exc}"
            ) from None
        return meta, len(MAGIC) + 4 + hlen

    def _validate(self, meta: dict, key: dict, name: str) -> None:
        if meta.get("key") != key or meta.get("name") != name:
            raise StaleAotArtifact(
                f"artifact keyed {meta.get('key')}/{meta.get('name')}, "
                f"wanted {key}/{name}"
            )
        fp = env_fingerprint()
        if meta.get("fingerprint") != fp:
            raise StaleAotArtifact(
                f"environment fingerprint {meta.get('fingerprint')} != "
                f"current {fp}"
            )

    def probe(self, key: dict, name: str | None = None) -> bool:
        """Read-only readiness check: does a fingerprint-current core
        artifact with an INTACT payload exist? The registry names its
        build span ``engine_adopt`` vs ``engine_build`` off this, and
        the span name is the no-compile signal the preheat smoke
        asserts — so the probe verifies the payload CRC too (a valid
        header over a torn/rotted payload must read as NOT adoptable,
        not as a phantom adoption). Side-effect free: no quarantine, no
        counter — :meth:`get` takes the consequential actions."""
        key = program_key(key)
        names = CORE_NAMES if name is None else (name,)
        for n in names:
            path = self.path_for(key, n)
            if not os.path.exists(path):
                continue
            try:
                meta, off = self._read_header(path)
                self._validate(meta, key, n)
                with open(path, "rb") as f:
                    f.seek(off)
                    payload = f.read()
                if _crc32(payload) != meta.get("payload_crc32"):
                    continue
                return True
            except (AotArtifactError, OSError):
                continue
        return False

    def get(self, key: dict, name: str, *, with_meta: bool = False):
        """The validated payload (or ``(payload, meta)`` under
        ``with_meta``), or None with the degrade applied: missing/stale
        -> fallback counted; corrupt -> quarantined + fallback counted.
        Never raises on a bad artifact — the JIT path always serves."""
        key = program_key(key)
        path = self.path_for(key, name)
        if not os.path.exists(path):
            self._bump("fallbacks")
            return None
        try:
            meta, off = self._read_header(path)
            self._validate(meta, key, name)
            with open(path, "rb") as f:
                f.seek(off)
                payload = f.read()
            if _faults.ACTIVE is not None:
                # Chaos-harness injection site (tpu_bfs/faults.py):
                # corrupt_aot flips one payload byte in memory so the CRC
                # check below fires deterministically; raising kinds
                # surface here like a real storage-layer failure.
                _faults.ACTIVE.hit("aot_load", name=name, lanes=key["lanes"])
                payload = _faults.maybe_corrupt_payload(
                    payload, name=name, lanes=key["lanes"]
                )
            if _crc32(payload) != meta.get("payload_crc32"):
                raise CorruptAotArtifact("payload CRC32 mismatch")
        except CorruptAotArtifact as exc:
            self._quarantine(path, str(exc))
            self._bump("fallbacks")
            return None
        except StaleAotArtifact as exc:
            self._log(f"aot artifact stale ({exc}); falling back to JIT")
            self._bump("fallbacks")
            return None
        except (OSError, RuntimeError) as exc:
            # Includes injected transients: a flaky artifact read must
            # degrade to JIT, never kill a preheat.
            self._log(f"aot artifact load failed ({exc!r}); falling back "
                      f"to JIT")
            self._bump("fallbacks")
            return None
        self._bump("hits")
        return (payload, meta) if with_meta else payload


class AdoptedProgram:
    """A deserialized AOT executable standing in for an engine's jit
    entry.

    Calls whose argument shapes match the exported signature run the
    deserialized program (under one ``jax.jit`` wrapper, so repeated
    dispatch is cached exactly like the original pjit entry); anything
    else — a narrower one-shot batch, a resume entry — falls back to the
    ORIGINAL jit function, so correctness never depends on the artifact.
    Exposes ``_cache_size`` like a pjit function, so the analysis trace
    sentinel (PR 8 pass 2, analysis/transfer.py) covers adopted engines
    without per-engine plumbing.
    """

    def __init__(self, name: str, exported, original, store=None,
                 donate_argnums=()):
        import jax

        self.name = name
        self._exported = exported
        # Export-side consumers reach through the wrapper for the
        # original traceable (re-exporting from an adopted server).
        self._aot_original = original
        self._store = store
        # Donation re-applied from the artifact header (ISSUE 13):
        # jax.export strips it, and an adopted resume core that copies
        # its carry would double the residency the donation removed.
        self._donate_argnums = tuple(donate_argnums)
        self._jit = (
            jax.jit(exported.call, donate_argnums=self._donate_argnums)
            if self._donate_argnums else jax.jit(exported.call)
        )
        self._in_shapes = tuple(tuple(a.shape) for a in exported.in_avals)
        self.calls = 0
        self.fallback_calls = 0

    def _matches(self, args) -> bool:
        import jax

        leaves = jax.tree_util.tree_leaves(args)
        if len(leaves) != len(self._in_shapes):
            return False
        for leaf, shape in zip(leaves, self._in_shapes):
            if tuple(np.shape(leaf)) != shape:
                return False
        return True

    def __call__(self, *args):
        if not self._matches(args):
            self.fallback_calls += 1
            if self._store is not None:
                self._store._bump("runtime_fallbacks")
            return self._aot_original(*args)
        self.calls += 1
        return self._jit(*args)

    def _cache_size(self) -> int:
        size = getattr(self._jit, "_cache_size", None)
        return size() if callable(size) else 0


def export_available() -> bool:
    try:
        from jax import export as _  # noqa: F401

        return True
    except ImportError:
        return False


def export_engine_programs(engine, spec, store: ArtifactStore, *,
                           log=None) -> list:
    """Export every program in ``engine.export_programs()`` into the
    store under the registry key for ``spec``. Per-program failures
    (e.g. an exporter that cannot handle a sharded core on this jax)
    log and skip — the store holds what it can, the JIT path serves the
    rest. Returns the exported names."""
    from jax import export as jexp

    log = log or (lambda msg: None)
    if not hasattr(engine, "export_programs"):
        # Workload adapters (ISSUE 14) carry no AOT inventory (their
        # base substrate's programs export under the kind="bfs" key;
        # the adapters' own state — weighted tiles, cached CC index —
        # is data, not programs): nothing to export, JIT serves.
        return []
    key = program_key(spec)
    done = []
    for name, _attr, fn, args in engine.export_programs():
        # Re-exporting from an adopted engine must serialize the
        # original traceable, not the wrapper.
        fn = getattr(fn, "_aot_original", fn)
        with _obs.maybe_span(
            "aot_export", f"{key['engine']}-w{key['lanes']}-{name}",
            cat="aot", program=name, width=key["lanes"],
        ):
            try:
                exported = jexp.export(fn)(*args)
                store.put(
                    key, name, exported.serialize(),
                    donate_argnums=getattr(fn, "_donate_argnums", ()),
                )
            except Exception as exc:  # noqa: BLE001 — per-program degrade
                log(f"aot export of {name!r} failed "
                    f"({type(exc).__name__}: {str(exc)[:160]}); skipped")
                continue
        done.append(name)
    return done


def adopt_engine_programs(engine, spec, store: ArtifactStore, *,
                          log=None) -> list:
    """Load, deserialize, and INSTALL the store's programs over the
    engine's jit entries (``engine.adopt_programs``). Missing/stale/
    corrupt artifacts are skipped (the store counts the fallback and
    the engine keeps its JIT entry for that program). Returns the
    adopted names."""
    from jax import export as jexp

    log = log or (lambda msg: None)
    if not hasattr(engine, "export_programs"):
        return []  # workload adapter: no inventory, JIT serves (above)
    key = program_key(spec)
    programs = {}
    for name, _attr, fn, _args in engine.export_programs():
        with _obs.maybe_span(
            "aot_load", f"{key['engine']}-w{key['lanes']}-{name}",
            cat="aot", program=name, width=key["lanes"],
        ):
            got = store.get(key, name, with_meta=True)
            if got is None:
                continue
            payload, meta = got
            try:
                exported = jexp.deserialize(payload)
            except Exception as exc:  # noqa: BLE001 — CRC-clean but unloadable
                store._quarantine(
                    store.path_for(key, name),
                    f"deserialize failed: {type(exc).__name__}: "
                    f"{str(exc)[:160]}",
                )
                store._bump("fallbacks")
                continue
        programs[name] = AdoptedProgram(
            name, exported, fn, store=store,
            donate_argnums=meta.get("donate_argnums", ()),
        )
    adopted = engine.adopt_programs(programs)
    if adopted:
        log(f"aot adopted {adopted} for {key['engine']}/w{key['lanes']}")
    return adopted
