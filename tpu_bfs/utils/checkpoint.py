"""Checkpoint / resume of BFS traversal state and results.

The reference has no checkpointing at all (SURVEY.md §5: "BFS state is
per-run; results live only in process memory") — a failed rank hangs the
MPI_Allreduce (bfs_mpi.cu:621) and the whole traversal is lost. Here the
traversal state (frontier / visited / distance + level counter) is an explicit
value: engines expose ``start`` / ``advance`` / ``finish``, and this module
persists checkpoints either as one ``.npz`` or as per-shard files (one per
chip of a 1D partition) that can be re-assembled under a *different* shard
count — elastic restart, which the reference's compile-time DeviceNum
(bfs.cu:19) and fixed 2-rank world cannot express.

Results (``BfsResult``) round-trip through ``save_result``/``load_result``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zipfile
import zlib

import numpy as np

from tpu_bfs import faults as _faults

_STATE_VERSION = 1


class CorruptCheckpointError(ValueError):
    """An on-disk checkpoint failed its integrity check (payload CRC32
    mismatch, or unreadable npz). The offending file has been QUARANTINED
    (renamed ``<path>.corrupt``) so a retry loop can never resume from
    poisoned state; the message names the exact file/shard."""


def _payload_crc32(arrays: dict) -> int:
    """CRC32 over every array's name, dtype, shape, and bytes — the
    integrity record written into each checkpoint npz on save and
    verified on load. Key-order independent (sorted), so save and load
    agree regardless of kwargs order."""
    crc = 0
    for name in sorted(arrays):
        a = np.asarray(arrays[name])
        crc = zlib.crc32(f"{name}:{a.dtype.str}:{a.shape}".encode(), crc)
        # The contiguous ndarray feeds crc32 through the buffer protocol
        # directly — no tobytes() copy, which would transiently double
        # peak host memory on exactly the memory-pressured runs where
        # checkpointing matters most.
        crc = zlib.crc32(np.ascontiguousarray(a), crc)
    return crc


def _quarantine(path: str, reason: str) -> None:
    qpath = path + ".corrupt"
    try:
        os.replace(path, qpath)
    except OSError:
        qpath = path  # read-only fs: still refuse to load it
    raise CorruptCheckpointError(
        f"checkpoint {path} failed integrity verification ({reason}); "
        f"quarantined as {qpath} — resume from an intact checkpoint"
    )


def _load_npz_verified(path: str) -> dict:
    """Load an npz written by ``_atomic_savez`` and verify its payload
    CRC32. Unreadable or mismatching files are quarantined (renamed
    ``.corrupt``) and raise :class:`CorruptCheckpointError` naming the
    file. Files written before the CRC field existed load unverified."""
    if _faults.ACTIVE is not None:
        _faults.ACTIVE.hit("ckpt_load", path=path)
    try:
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
    except (zipfile.BadZipFile, zlib.error, EOFError, ValueError,
            KeyError) as exc:
        # DECODE failures only: quarantine is irreversible, so resource
        # blips that say nothing about the bytes on disk (MemoryError
        # mid-decompression, a transient OSError from a flaky mount)
        # must propagate without destroying an intact checkpoint.
        _quarantine(path, f"unreadable: {type(exc).__name__}: {exc}")
    crc = arrays.pop("payload_crc32", None)
    if crc is not None and int(crc) != _payload_crc32(arrays):
        _quarantine(path, "payload CRC32 mismatch")
    return arrays


def _new_nonce() -> int:
    """Random chain id for exchange-accounting identity (see BfsCheckpoint)."""
    return int.from_bytes(os.urandom(8), "little") >> 1  # fits int64


@dataclasses.dataclass
class BfsCheckpoint:
    """Host-side snapshot of one traversal, in REAL vertex-id space [V].

    Engines convert to their own padded/sharded layouts on entry, so a
    checkpoint taken on one engine/mesh resumes on any other over the same
    graph. ``level`` is the level-loop counter (number of completed level
    steps); resuming with ``engine.advance`` continues distance labeling
    bit-identically to an uninterrupted run.
    """

    source: int
    level: int
    frontier: np.ndarray  # [V] bool
    visited: np.ndarray  # [V] bool
    distance: np.ndarray  # [V] int32 (INF_DIST where unreached)
    # Chain identity for exchange-byte accounting: generated once per
    # start(), carried through every chunk, so an engine merges resumed
    # level counters only into the traversal they belong to (never into
    # counters left by an unrelated run that happened to reach the same
    # level — the coincidence the old sum-check alone allowed). None on
    # checkpoints written before the field existed.
    nonce: int | None = None

    @property
    def done(self) -> bool:
        """True once the frontier is empty (the traversal has terminated)."""
        return not bool(self.frontier.any())


def initial_checkpoint(num_vertices: int, source: int) -> BfsCheckpoint:
    """Level-0 traversal state: frontier = visited = {source}, dist[source]=0.

    Shared by every engine's ``start`` so cross-engine checkpoints cannot
    drift (the conventions here are load-bearing for portability)."""
    from tpu_bfs.graph.csr import INF_DIST

    if not (0 <= source < num_vertices):
        raise ValueError(f"source {source} out of range [0, {num_vertices})")
    frontier = np.zeros(num_vertices, dtype=bool)
    frontier[source] = True
    dist = np.full(num_vertices, INF_DIST, dtype=np.int32)
    dist[source] = 0
    return BfsCheckpoint(
        source=source, level=0, frontier=frontier,
        visited=frontier.copy(), distance=dist, nonce=_new_nonce(),
    )


def _atomic_savez(path: str, **arrays) -> None:
    """savez_compressed to exactly ``path``, atomically, with integrity.

    A file handle (not a bare path) stops ``np.savez_compressed`` from
    appending ``.npz`` — which would make ``--ckpt state`` save ``state.npz``
    while ``--resume state`` opens ``state`` and fails. Writing to a sibling
    temp file and ``os.replace``-ing keeps the previous good checkpoint
    intact if the process dies mid-save — the exact failure checkpointing
    exists to survive. A ``payload_crc32`` field rides in the npz so the
    load path can detect bit-level corruption (``_load_npz_verified``)
    instead of silently resuming from a flipped table."""
    if _faults.ACTIVE is not None:
        _faults.ACTIVE.hit("ckpt_save", path=path)
    tmp = f"{path}.tmp"
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f, payload_crc32=np.uint32(_payload_crc32(arrays)), **arrays
            )
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    # Chaos-harness corruption (corrupt_ckpt rules) happens AFTER the
    # completed atomic write — simulating storage corruption, which the
    # CRC above exists to catch on the next load.
    if _faults.ACTIVE is not None:
        _faults.maybe_corrupt_file(path)


def save_checkpoint(path: str, ckpt: BfsCheckpoint) -> None:
    """Write a checkpoint as one ``.npz`` file, at exactly ``path``."""
    _atomic_savez(
        path,
        version=_STATE_VERSION,
        source=ckpt.source,
        level=ckpt.level,
        frontier=ckpt.frontier,
        visited=ckpt.visited,
        distance=ckpt.distance,
        nonce=-1 if ckpt.nonce is None else ckpt.nonce,
    )


def load_checkpoint(path: str) -> BfsCheckpoint:
    z = _load_npz_verified(path)
    if int(z["version"]) != _STATE_VERSION:
        raise ValueError(f"unsupported checkpoint version {int(z['version'])}")
    if "kind" in z and str(z["kind"]) == "packed":
        raise ValueError(
            f"{path} is a packed-batch checkpoint (use load_packed_checkpoint"
            " / resume it with a multi-source engine)"
        )
    nonce = int(z["nonce"]) if "nonce" in z else -1
    return BfsCheckpoint(
        source=int(z["source"]),
        level=int(z["level"]),
        frontier=z["frontier"],
        visited=z["visited"],
        distance=z["distance"],
        nonce=None if nonce < 0 else nonce,
    )


@dataclasses.dataclass
class PackedCheckpoint:
    """Host-side snapshot of one packed multi-source batch traversal.

    All tables are in REAL vertex-id row order ([V, w] uint32; lane ``l``
    of batch entry order at word ``l // 32``, bit ``l % 32`` — the packed
    engines' shared word-major lane map), so a checkpoint taken on one
    packed engine resumes on any other over the same graph and lane count
    (wide gather-only or hybrid MXU+gather). ``planes`` are the bit-sliced
    distance counters ([P, V, w]); ``level`` is the completed level-step
    count; ``alive`` is False once a step claimed nothing (terminated).

    The reference checkpoints nothing (SURVEY.md §5) — and its per-source
    process loop (bfs.cu:783-823) has no batch state to save in the first
    place; this persists the expensive thing at scale: the whole 4096-lane
    traversal's packed state.
    """

    sources: np.ndarray  # [S] int64
    level: int
    alive: bool
    frontier: np.ndarray  # [V, w] uint32
    visited: np.ndarray  # [V, w] uint32
    planes: np.ndarray  # [P, V, w] uint32
    # [S] bool: lanes whose source is isolated (no row in trimmed engine
    # tables; the component is trivially {source}). Recorded at start()
    # from the starting engine — which knows it exactly — so ANY finishing
    # engine can patch those lanes, including one built from a prebuilt
    # directed shard set that cannot reconstruct the mask itself
    # (dist_msbfs_wide._iso_mask = None). None on old checkpoints.
    iso: np.ndarray | None = None
    # Chain identity for exchange accounting (see BfsCheckpoint.nonce).
    nonce: int | None = None

    @property
    def done(self) -> bool:
        return not self.alive


def save_packed_checkpoint(path: str, ckpt: PackedCheckpoint) -> None:
    """Write a packed-batch checkpoint as one ``.npz``, at exactly ``path``."""
    _atomic_savez(
        path,
        version=_STATE_VERSION,
        kind="packed",
        sources=ckpt.sources,
        level=ckpt.level,
        alive=int(ckpt.alive),
        frontier=ckpt.frontier,
        visited=ckpt.visited,
        planes=ckpt.planes,
        iso=np.empty(0, bool) if ckpt.iso is None else ckpt.iso.astype(bool),
        nonce=-1 if ckpt.nonce is None else ckpt.nonce,
    )


def load_packed_checkpoint(path: str) -> PackedCheckpoint:
    z = _load_npz_verified(path)
    if int(z["version"]) != _STATE_VERSION:
        raise ValueError(f"unsupported checkpoint version {int(z['version'])}")
    if "kind" not in z or str(z["kind"]) != "packed":
        raise ValueError(
            f"{path} is not a packed-batch checkpoint (use load_checkpoint "
            "for single-source state)"
        )
    iso = z["iso"] if "iso" in z else np.empty(0, bool)
    nonce = int(z["nonce"]) if "nonce" in z else -1
    return PackedCheckpoint(
        sources=z["sources"].astype(np.int64),
        level=int(z["level"]),
        alive=bool(int(z["alive"])),
        frontier=z["frontier"],
        visited=z["visited"],
        planes=z["planes"],
        iso=iso.astype(bool) if iso.size else None,
        nonce=None if nonce < 0 else nonce,
    )


def save_checkpoint_sharded(dirpath: str, ckpt: BfsCheckpoint, num_shards: int) -> None:
    """Write one file per shard of a ``num_shards``-way contiguous 1D split.

    Shard k owns real vertex ids [k*cpk, min((k+1)*cpk, V)) with
    cpk = ceil(V / num_shards) — the same ownership map as ``partition_1d``.
    Layout: ``meta.json`` + ``shard_00000.npz`` ... Because shards are in real
    id space, the re-assembled checkpoint resumes on any mesh size.
    """
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    v = len(ckpt.frontier)
    if num_shards > v:
        raise ValueError(f"num_shards={num_shards} exceeds vertex count {v}")
    cpk = -(-v // num_shards)
    os.makedirs(dirpath, exist_ok=True)
    # Two-generation layout: the complete new shard set is written into the
    # inactive generation subdir, and only then does meta.json (written
    # atomically, last) flip to point at it. A crash anywhere mid-save
    # leaves the previous generation untouched and still referenced — the
    # prior checkpoint survives, which is the whole point of checkpointing.
    # Every shard also embeds its level; load cross-checks it against meta
    # so any inconsistency fails loudly instead of mixing levels' slices.
    meta_path = os.path.join(dirpath, "meta.json")
    prev_gen = None
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                prev_gen = json.load(f).get("generation")
        except (OSError, json.JSONDecodeError):
            prev_gen = None
    gen = "gen_b" if prev_gen == "gen_a" else "gen_a"
    gen_dir = os.path.join(dirpath, gen)
    os.makedirs(gen_dir, exist_ok=True)
    meta = {
        "version": _STATE_VERSION,
        "source": int(ckpt.source),
        "level": int(ckpt.level),
        "num_vertices": v,
        "num_shards": num_shards,
        "generation": gen,
        "nonce": ckpt.nonce,  # chain identity (None on old checkpoints)
    }
    # Clear stale files from an earlier save of this generation FIRST: a
    # re-shard to fewer shards (elastic restart on a smaller mesh) must
    # not leave old-level shard_000NN.npz files behind — the fallback
    # loader derives a generation's shard count from its directory, and
    # stale extras would make an intact generation look torn. Earlier
    # quarantines (.corrupt) are cleared too; they documented a failure
    # this save supersedes.
    for fname in os.listdir(gen_dir):
        if not fname.startswith("shard_"):
            continue
        if fname.endswith(".npz.corrupt"):
            stale = True
        elif fname.endswith(".npz"):
            try:
                stale = not 0 <= int(fname[len("shard_"):-len(".npz")]) < num_shards
            except ValueError:
                stale = False  # not ours; leave it
        else:
            continue
        if stale:
            try:
                os.unlink(os.path.join(gen_dir, fname))
            except OSError:
                pass
    for k in range(num_shards):
        sl = slice(k * cpk, min((k + 1) * cpk, v))
        _atomic_savez(
            os.path.join(gen_dir, f"shard_{k:05d}.npz"),
            level=ckpt.level,
            # Traversal identity rides in every shard (not just meta):
            # the corruption fallback loads a PREVIOUS generation, whose
            # meta was overwritten by the newer save — without these a
            # reused checkpoint dir could silently resume another run's
            # arrays under this run's source label.
            source=ckpt.source,
            nonce=-1 if ckpt.nonce is None else ckpt.nonce,
            frontier=ckpt.frontier[sl],
            visited=ckpt.visited[sl],
            distance=ckpt.distance[sl],
        )
    tmp = meta_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, meta_path)


def _load_sharded_generation(
    dirpath: str, meta: dict, gen: str | None, *, expect_level: int | None
) -> BfsCheckpoint:
    """Assemble one generation's shard set. ``expect_level`` cross-checks
    each shard against meta (the active generation); None accepts any one
    consistent level (a fallback generation — meta describes the newer,
    lost one), returning whatever level its shards agree on."""
    shard_dir = os.path.join(dirpath, gen) if gen else dirpath
    num_shards = int(meta["num_shards"])
    if expect_level is None:
        # Fallback generation: meta describes the NEWER (lost) save, whose
        # shard count may differ (re-sharding across mesh sizes is a
        # documented use) — derive the count from the generation's own
        # files; the num_vertices cross-check below still rejects a torn
        # or incomplete set.
        num_shards = len([
            f for f in os.listdir(shard_dir)
            if f.startswith("shard_") and f.endswith(".npz")
        ])
        if num_shards == 0:
            raise FileNotFoundError(f"no shards in {shard_dir}")
    parts = []
    level = expect_level
    source = nonce = None
    for k in range(num_shards):
        p = _load_npz_verified(os.path.join(shard_dir, f"shard_{k:05d}.npz"))
        # Shards written before this field existed load as level-consistent.
        lvl = int(p["level"]) if "level" in p else int(meta["level"])
        if level is None:
            level = lvl
        if lvl != level:
            raise ValueError(
                f"torn sharded checkpoint: shard {k} is from level {lvl} "
                f"but {'meta.json records' if expect_level is not None else 'its siblings are from'} "
                f"level {level} — the save was interrupted; re-checkpoint "
                f"before resuming"
            )
        if "source" in p:
            src = int(p["source"])
            if source is None:
                source = src
            if src != source:
                raise ValueError(
                    f"torn sharded checkpoint: shard {k} is from source "
                    f"{src} but its siblings are from source {source}"
                )
            if "nonce" in p:
                n = int(p["nonce"])
                nonce = None if n < 0 else n
        parts.append(p)
    # Identity comes from the shards themselves when recorded: a fallback
    # generation may predate the traversal meta.json now describes (a
    # reused checkpoint dir), and stamping its arrays with the newer
    # source would silently resume the wrong run. Shards without the
    # field (pre-integrity saves) fall back to meta.
    if source is None:
        source, nonce = int(meta["source"]), meta.get("nonce")
    elif expect_level is not None and source != int(meta["source"]):
        raise ValueError(
            f"sharded checkpoint source mismatch: shards record source "
            f"{source} but meta.json records {meta['source']}"
        )
    ckpt = BfsCheckpoint(
        source=source,
        level=int(level),
        frontier=np.concatenate([p["frontier"] for p in parts]),
        visited=np.concatenate([p["visited"] for p in parts]),
        distance=np.concatenate([p["distance"] for p in parts]),
        nonce=nonce,
    )
    if len(ckpt.frontier) != int(meta["num_vertices"]):
        raise ValueError("shard sizes do not add up to the recorded vertex count")
    return ckpt


def load_checkpoint_sharded(dirpath: str, *, log=None) -> BfsCheckpoint:
    """Re-assemble a sharded checkpoint into one host checkpoint.

    The result is shard-count-agnostic: resume it on any mesh whose engine
    shares the same padded vertex count. A corrupt shard in the active
    generation is quarantined (``.corrupt``) and the load FALLS BACK to
    the previous generation — the newest intact checkpoint — instead of
    failing outright or resuming from poisoned state; only when both
    generations are damaged does the corruption error propagate.
    """
    with open(os.path.join(dirpath, "meta.json")) as f:
        meta = json.load(f)
    if int(meta["version"]) != _STATE_VERSION:
        raise ValueError(f"unsupported checkpoint version {meta['version']}")
    # Generation layout; checkpoints written before it load from the flat dir.
    gen = meta.get("generation")
    try:
        return _load_sharded_generation(
            dirpath, meta, gen, expect_level=int(meta["level"])
        )
    except (CorruptCheckpointError, FileNotFoundError) as exc:
        # FileNotFoundError covers a RE-load after a shard was already
        # quarantined (renamed .corrupt) by an earlier attempt — e.g. a
        # crash-between-quarantine-and-resume, or a retry loop: the
        # fallback must still reach the intact generation.
        prev = {"gen_a": "gen_b", "gen_b": "gen_a"}.get(gen)
        if prev is None or not os.path.isdir(os.path.join(dirpath, prev)):
            raise
        if log is not None:
            log(f"active generation {gen} is corrupt ({exc}); falling back "
                f"to the previous generation {prev}")
        try:
            back = _load_sharded_generation(
                dirpath, meta, prev, expect_level=None
            )
            if back.source != int(meta["source"]):
                # A reused checkpoint dir: the previous generation is an
                # intact checkpoint of a DIFFERENT traversal — falling
                # back to it would resume the wrong run.
                raise CorruptCheckpointError(
                    f"fallback generation {prev} records source "
                    f"{back.source}, not this traversal's "
                    f"{meta['source']}"
                )
            return back
        except (ValueError, FileNotFoundError) as exc2:
            # ValueError covers CorruptCheckpointError AND a torn/short
            # fallback set — either way both generations are unusable.
            raise CorruptCheckpointError(
                f"no intact checkpoint generation in {dirpath}: "
                f"active {gen}: {exc}; fallback {prev}: {exc2}"
            ) from exc2


def save_result(path: str, res) -> None:
    """Persist a BfsResult (distance + parent outputs) as ``.npz``.

    The reference prints nothing durable — results die with the process
    (SURVEY.md §5); this is the ``--save-dist``/``--save-parent`` capability
    in one artifact with provenance fields.
    """
    _atomic_savez(
        path,
        version=_STATE_VERSION,
        source=res.source,
        distance=res.distance,
        parent=res.parent if res.parent is not None else np.empty(0, np.int32),
        num_levels=res.num_levels,
        reached=res.reached,
        edges_traversed=res.edges_traversed,
    )


def load_result(path: str):
    from tpu_bfs.algorithms.bfs import BfsResult

    z = _load_npz_verified(path)
    if int(z["version"]) != _STATE_VERSION:
        raise ValueError(f"unsupported result version {int(z['version'])}")
    parent = z["parent"]
    return BfsResult(
        source=int(z["source"]),
        distance=z["distance"],
        parent=parent if parent.size else None,
        num_levels=int(z["num_levels"]),
        reached=int(z["reached"]),
        edges_traversed=int(z["edges_traversed"]),
    )
