"""Persistent XLA compilation cache, shared by bench.py and the
measurement scripts (scripts/width_probe.py).

First compiles of the packed level loop cost ~20-40 s on the chip and
recur in every fresh process; during an outage-recovery session that is
wall-clock the bench's budget envelope cannot spare. One copy of the
env-var resolution so the two callers cannot drift into writing separate
caches (TPU_BFS_BENCH_XLA_CACHE, default <TPU_BFS_BENCH_CACHE>/xla_cache;
empty disables).
"""

from __future__ import annotations

import os


def enable_compile_cache(log=None) -> str | None:
    """Point jax at the persistent compile cache; best-effort.

    Returns the cache path when enabled, None when disabled or
    unavailable (a jax without the knob degrades to the status quo).
    """
    path = os.environ.get(
        "TPU_BFS_BENCH_XLA_CACHE",
        os.path.join(
            os.environ.get("TPU_BFS_BENCH_CACHE", ".bench_cache"), "xla_cache"
        ),
    )
    if not path:
        return None
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        if log:
            log(f"persistent compile cache: {path}")
        return path
    except Exception as exc:  # noqa: BLE001 — the cache is an optimization
        if log:
            log(f"compile cache unavailable ({exc!r}); continuing without")
        return None
