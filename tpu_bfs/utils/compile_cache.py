"""Persistent XLA compilation cache, shared by bench.py and the
measurement scripts (scripts/width_probe.py).

First compiles of the packed level loop cost ~20-40 s on the chip and
recur in every fresh process; during an outage-recovery session that is
wall-clock the bench's budget envelope cannot spare. One copy of the
env-var resolution so the two callers cannot drift into writing separate
caches (TPU_BFS_BENCH_XLA_CACHE, default <TPU_BFS_BENCH_CACHE>/xla_cache;
empty disables).

Resolution is ONCE PER PROCESS: every ``EngineRegistry()`` construction
and every bench entry calls :func:`enable_compile_cache`, and before the
idempotency guard each call re-ran ``jax.config.update`` and re-logged
the path — a preheat run constructing registries per service spammed the
log and re-pointed jax at a cache it was already using. The first call's
outcome (path or disabled) is cached; later calls return it silently.
``force=True`` re-resolves (tests that vary the env).
"""

from __future__ import annotations

import os

# The first call's resolved outcome, kept as a 1-tuple so a resolved
# "disabled" (None) is distinguishable from "never resolved".
_RESOLVED: tuple | None = None


def reset_resolution() -> None:
    """Forget the cached resolution (tests that vary the env vars)."""
    global _RESOLVED
    _RESOLVED = None


def enable_compile_cache(log=None, *, force: bool = False) -> str | None:
    """Point jax at the persistent compile cache; best-effort and
    idempotent (resolved once per process — see module docstring).

    Returns the cache path when enabled, None when disabled or
    unavailable (a jax without the knob degrades to the status quo).
    """
    global _RESOLVED
    if _RESOLVED is not None and not force:
        return _RESOLVED[0]
    path = os.environ.get(
        "TPU_BFS_BENCH_XLA_CACHE",
        os.path.join(
            os.environ.get("TPU_BFS_BENCH_CACHE", ".bench_cache"), "xla_cache"
        ),
    )
    if not path:
        _RESOLVED = (None,)
        return None
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        if log:
            log(f"persistent compile cache: {path}")
        _RESOLVED = (path,)
        return path
    except Exception as exc:  # noqa: BLE001 — the cache is an optimization
        if log:
            log(f"compile cache unavailable ({exc!r}); continuing without")
        _RESOLVED = (None,)
        return None
