"""Native (C++) fast paths, loaded via ctypes.

The reference's loader and CSR build are C++ (readGraphFromFile,
bfs.cu:829-880); the equivalents here live in ``tpu_bfs/native/`` (inside
the package, so wheels ship the sources namespaced) and are compiled to
``libtpubfs.so``. Everything degrades gracefully to the NumPy
implementations when the shared library has not been built.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None
_TRIED = False


def _native_dir() -> str:
    """``tpu_bfs/native/`` — a sibling of this file's parent package, so
    the lookup survives both a checkout and an installed wheel."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "native",
    )


def _find_lib():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    for cand in (
        os.path.join(_native_dir(), "build", "libtpubfs.so"),
        os.path.join(_native_dir(), "libtpubfs.so"),
    ):
        if os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
                lib.tpubfs_parse_edge_list.restype = ctypes.c_longlong
                lib.tpubfs_parse_edge_list.argtypes = [
                    ctypes.c_char_p,  # path
                    ctypes.POINTER(ctypes.c_longlong),  # out n
                    ctypes.POINTER(ctypes.c_longlong),  # out m
                    ctypes.POINTER(ctypes.POINTER(ctypes.c_longlong)),  # out u
                    ctypes.POINTER(ctypes.POINTER(ctypes.c_longlong)),  # out v
                ]
                lib.tpubfs_free.argtypes = [ctypes.POINTER(ctypes.c_longlong)]
                lib.tpubfs_lexsort_pairs.restype = ctypes.c_longlong
                lib.tpubfs_lexsort_pairs.argtypes = [
                    ctypes.POINTER(ctypes.c_longlong),
                    ctypes.POINTER(ctypes.c_longlong),
                    ctypes.c_longlong,
                    ctypes.c_longlong,
                    ctypes.c_longlong,
                    ctypes.POINTER(ctypes.c_longlong),
                ]
                # Newer symbol: a stale build keeps the older fast paths and
                # only loses the generator (rmat_edges_native checks again).
                if getattr(lib, "tpubfs_rmat_edges", None) is not None:
                    lib.tpubfs_rmat_edges.restype = ctypes.c_longlong
                    lib.tpubfs_rmat_edges.argtypes = [
                        ctypes.c_longlong,  # scale
                        ctypes.c_longlong,  # m
                        ctypes.c_longlong,  # seed
                        ctypes.c_double,  # a
                        ctypes.c_double,  # b
                        ctypes.c_double,  # c
                        ctypes.POINTER(ctypes.c_longlong),  # out u
                        ctypes.POINTER(ctypes.c_longlong),  # out v
                    ]
                _LIB = lib
                break
            except (OSError, AttributeError):
                pass  # missing lib or stale build without newer symbols
    return _LIB


def ensure_built(log=None) -> None:
    """Best-effort ``make -C tpu_bfs/native`` so a fresh (or stale) checkout gets the
    fast paths. make itself is the up-to-date check (~ms when current).

    Must run before the first library lookup in the process: the ctypes
    handle is cached on first use and a replaced .so does not affect an
    already-loaded image. ``log`` (a callable taking one string) receives a
    diagnostic when the build fails; callers then fall back to NumPy paths
    via ``available()``/``has_rmat()``.
    """
    import signal
    import subprocess

    def _unblock_signals() -> None:
        # bench.py's signal envelope blocks SIGTERM/SIGINT process-wide
        # (sigwait watcher), and the mask is inherited across fork+exec —
        # without this, a driver's group-kill could leave make (and its
        # compiler children) unkillable and lingering past the parent.
        # pthread_sigmask is async-signal-safe, so it is preexec-legal.
        signal.pthread_sigmask(
            signal.SIG_UNBLOCK, (signal.SIGTERM, signal.SIGINT)
        )

    try:
        proc = subprocess.run(
            ["make", "-C", _native_dir()],
            capture_output=True, timeout=120, check=False, text=True,
            preexec_fn=_unblock_signals,
        )
        if proc.returncode != 0 and log is not None:
            log(
                f"native build failed (rc={proc.returncode}); falling back "
                f"to numpy paths: {proc.stderr.strip()[-300:]}"
            )
    except (OSError, subprocess.TimeoutExpired) as exc:
        if log is not None:
            log(f"native build skipped: {exc}")


def available() -> bool:
    return _find_lib() is not None


def has_rmat() -> bool:
    """True iff the loaded library exports the RMAT generator — a stale
    prebuilt .so can load fine yet predate tpubfs_rmat_edges, in which case
    ``rmat_graph(impl='native')`` would raise instead of generating."""
    lib = _find_lib()
    return lib is not None and getattr(lib, "tpubfs_rmat_edges", None) is not None


def load_edge_list_native(path: str, *, directed: bool = False, drop_self_loops: bool = False):
    """Parse an edge-list file with the C++ loader. Returns a Graph, or None
    if the native library is unavailable (callers fall back to NumPy)."""
    lib = _find_lib()
    if lib is None:
        return None
    n = ctypes.c_longlong()
    m = ctypes.c_longlong()
    up = ctypes.POINTER(ctypes.c_longlong)()
    vp = ctypes.POINTER(ctypes.c_longlong)()
    rc = lib.tpubfs_parse_edge_list(
        path.encode(), ctypes.byref(n), ctypes.byref(m), ctypes.byref(up), ctypes.byref(vp)
    )
    if rc != 0:
        raise IOError(f"native loader failed on {path} (rc={rc})")
    try:
        u = np.ctypeslib.as_array(up, shape=(m.value,)).copy()
        v = np.ctypeslib.as_array(vp, shape=(m.value,)).copy()
    finally:
        lib.tpubfs_free(up)
        lib.tpubfs_free(vp)
    if drop_self_loops:
        keep = u != v
        u, v = u[keep], v[keep]
    from tpu_bfs.graph.io import from_edges

    return from_edges(
        u, v, num_vertices=int(n.value), directed=directed, num_input_edges=int(m.value)
    )


def rmat_edges_native(scale: int, m: int, seed: int, a: float, b: float, c: float):
    """Threaded native RMAT endpoints (native/rmat.cpp), or None if the
    library is unbuilt. Deterministic in (scale, m, seed, a, b, c) —
    independent of thread count — but a DIFFERENT stream than the NumPy
    generator's (same distribution, different graphs for the same seed)."""
    lib = _find_lib()
    if lib is None or getattr(lib, "tpubfs_rmat_edges", None) is None:
        return None  # library unbuilt, or a stale build without the symbol
    u = np.empty(m, dtype=np.int64)
    v = np.empty(m, dtype=np.int64)
    ll = ctypes.POINTER(ctypes.c_longlong)
    rc = lib.tpubfs_rmat_edges(
        int(scale), int(m), int(seed), float(a), float(b), float(c),
        u.ctypes.data_as(ll), v.ctypes.data_as(ll),
    )
    if rc != 0:
        raise ValueError(
            f"native RMAT generator rejected scale={scale}, m={m} (rc={rc})"
        )
    return u, v


def lexsort_pairs(major: np.ndarray, minor: np.ndarray, n_major: int, n_minor: int):
    """Permutation ordering by (major, minor) ascending — np.lexsort((minor,
    major)) semantics via an O(E) native counting sort. Returns None if the
    native library is unavailable (callers fall back to np.lexsort)."""
    lib = _find_lib()
    if lib is None:
        return None
    major = np.ascontiguousarray(major, dtype=np.int64)
    minor = np.ascontiguousarray(minor, dtype=np.int64)
    e = len(major)
    perm = np.empty(e, dtype=np.int64)
    ll = ctypes.POINTER(ctypes.c_longlong)
    rc = lib.tpubfs_lexsort_pairs(
        major.ctypes.data_as(ll),
        minor.ctypes.data_as(ll),
        e,
        int(n_major),
        int(n_minor),
        perm.ctypes.data_as(ll),
    )
    if rc != 0:
        return None
    return perm
