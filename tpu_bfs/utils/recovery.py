"""In-run failure detection + elastic recovery for checkpointed traversals.

SURVEY.md §5: the reference has no failure story at all — a failed rank
hangs the MPI_Allreduce (bfs_mpi.cu:621) and the whole traversal is lost.
Here the traversal state is an explicit host value (utils/checkpoint.py),
so recovery is a driver-level loop: classify the failure, rebuild the
engine (fresh device buffers + compiled programs), and resume from the
last durable checkpoint — bit-identical to never having failed, because
the while-loop carry IS the state. The same transient/deterministic
classifier guards the benchmark's compile-heavy stages (bench.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time

from tpu_bfs import faults as _faults


@dataclasses.dataclass
class RecoveryCounters:
    """Process-wide retry/degrade visibility (one instance: ``COUNTERS``).

    Until round 6 every retry here and in bench.py was invisible after
    the fact — a run that survived three transient failures and an OOM
    shed reported the same clean output as one that never hiccuped, so
    serve-mode incidents left no post-hoc trace. Every retry path now
    bumps these; the CLI's --stats emits them as a final JSON line and
    bench.py attaches them to its verdict line when any fired."""

    transient_retries: int = 0  # re-attempts after a transient classification
    engine_rebuilds: int = 0  # advance_with_recovery engine reconstructions
    backend_init_resets: int = 0  # reset_failed_backend_init firings
    oom_degrades: int = 0  # OOM-driven sheds/lane-halvings (bench + serve)
    watchdog_trips: int = 0  # serve dispatch-watchdog deadline firings
    breaker_opens: int = 0  # serve circuit-breaker open transitions
    requeue_sheds: int = 0  # queries shed at the serve requeue budget
    faults_injected: int = 0  # tpu_bfs/faults.py injections (chaos only)
    mesh_faults: int = 0  # mesh-death classifications (is_mesh_fault fired)
    mesh_degrades: int = 0  # degraded-mesh failover rebuilds (ISSUE 12)
    query_resumes: int = 0  # level-checkpointed mid-query resumes
    quarantines: int = 0  # corruption-audit rung quarantines (ISSUE 15)

    _lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def bump(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def as_dict(self) -> dict:
        with self._lock:
            return {
                f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)
                if f.name != "_lock"
            }

    def any(self) -> bool:
        return any(self.as_dict().values())

    def reset(self) -> None:
        with self._lock:
            for f in dataclasses.fields(self):
                if f.name != "_lock":
                    setattr(self, f.name, 0)


COUNTERS = RecoveryCounters()

# The jaxlib mesh-death strings (ISSUE 12): a participant dropping out
# of the slice surfaces as DATA_LOSS, a failed "slice health" check, or
# a "Program hung" collective timeout (the r03/r04 bench-outage class —
# see "Unable to initialize backend" below for the live failure string
# that motivated this family). ONE definition: these feed the transient
# patterns (a mesh fault is retryable infrastructure trouble) AND
# is_mesh_fault, which multi-chip callers consult to degrade the mesh
# instead of re-dispatching into the same dead collective.
MESH_FAULT_MARKERS = (
    "DATA_LOSS",
    "slice health",
    "Program hung",
)


def is_mesh_fault(exc: BaseException) -> bool:
    """True when ``exc`` carries a jaxlib mesh-death marker — the whole
    mesh's collectives are suspect, not just this dispatch. Callers with
    a single-chip engine treat these like any transient (retry in
    place); mesh-spanning callers run the degraded-mesh failover ladder
    (serve/executor.MeshFaultRequeue -> BfsService mesh degrade)."""
    msg = str(exc)
    return any(m in msg for m in MESH_FAULT_MARKERS)


# Substrings that mark an error as plausibly-transient infrastructure
# trouble: compile-service/transport failures and XLA's INTERNAL/UNAVAILABLE
# status codes. Bare "INTERNAL:" is included because infra errors don't
# always name their transport — the deny-list below catches the known
# deterministic INTERNAL shapes (Mosaic lowering bugs) so those surface on
# the first attempt.
TRANSIENT_PATTERNS = (
    "remote_compile",
    "read body closed",
    "Socket closed",
    "Connection reset",
    "Broken pipe",
    "INTERNAL:",
    "UNAVAILABLE:",
    "DEADLINE_EXCEEDED:",
    # jax raises a plain RuntimeError when no backend comes up at all —
    # observed live as "Unable to initialize backend 'axon': UNAVAILABLE:
    # TPU backend setup/compile error" after another tenant held the chip
    # through the client's whole polling window. The chip coming free later
    # is the common case, so this must be retryable (it killed a bench run
    # that round-2's retry machinery was specifically built to save).
    "Unable to initialize backend",
    *MESH_FAULT_MARKERS,
)

# Out-of-HBM flavors (XLA compile- or run-time). Deterministic — never
# retried — but callers with sheddable optional state (the bench's
# adaptive push table) use this to decide a plain re-run. ONE definition:
# an OOM variant added here is seen by both the transient classifier
# below and the bench's shed fallback.
OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "out of memory",
)


def is_oom_failure(exc: BaseException) -> bool:
    s = str(exc)
    low = s.lower()
    return any(m.lower() in low for m in OOM_MARKERS)


# Deterministic failures that can carry an INTERNAL: status but are bugs,
# not infra blips — retrying them burns minutes before surfacing the real
# error. OOM and shape/lowering errors are never transient.
NON_TRANSIENT_MARKERS = (
    "Mosaic",
    *OOM_MARKERS,
    "Invalid argument",
)

# Exception type names eligible for retry. Matched by name so the check
# works without importing jax at module import time. Validation failures
# (AssertionError, ValueError) are structurally excluded by this list.
# Plain RuntimeError is eligible because backend-init failures arrive as
# one (see TRANSIENT_PATTERNS) — but it still must carry a transient
# pattern in its message, so this framework's own RuntimeErrors (e.g. the
# plane-cap truncation raise, which signals a wrong configuration, not
# infrastructure) are never retried.
TRANSIENT_TYPE_NAMES = (
    "JaxRuntimeError",
    "XlaRuntimeError",
    "InternalError",
    "UnavailableError",
    "DeadlineExceededError",
    "RuntimeError",
)


def is_transient_failure(exc: BaseException) -> bool:
    """True for infrastructure-flavored runtime errors worth retrying —
    never for validation failures or deterministic compiler errors."""
    names = {t.__name__ for t in type(exc).__mro__}
    if not names.intersection(TRANSIENT_TYPE_NAMES):
        return False
    msg = str(exc)
    if any(p in msg for p in NON_TRANSIENT_MARKERS):
        return False
    return any(p in msg for p in TRANSIENT_PATTERNS)


# Backend-init failures need a longer wait than ordinary transients: the
# jax client already polled for the chip for its whole window before
# giving up, so the chip is likely held by another tenant for a while yet.
BACKEND_INIT_RETRY_FLOOR_S = 60.0


def reset_failed_backend_init(exc: BaseException, *, log=None) -> bool:
    """If ``exc`` is a backend-initialization failure ("Unable to
    initialize backend ...": no device ever came up, typically because
    another tenant held the chip through the client's whole polling
    window), clear jax's backend caches so the next attempt genuinely
    re-probes the hardware instead of re-raising the cached failure in
    milliseconds. Returns True when it fired — callers should then floor
    their backoff at BACKEND_INIT_RETRY_FLOOR_S.

    Only fires for init failures — at that point no device arrays exist
    anywhere, so clearing is safe. (After a mid-run failure the engines'
    device-resident arrays must survive the retry; never clear then.)"""
    if "Unable to initialize backend" not in str(exc):
        return False
    try:
        # jax.extend is a lazy submodule: must be imported explicitly
        # (plain `jax.extend.backend` AttributeErrors on jax 0.9).
        import jax.extend.backend as jax_backend

        jax_backend.clear_backends()
    except Exception as clear_exc:  # noqa: BLE001 — best-effort
        if log is not None:
            log(f"backend cache clear failed ({clear_exc!r}); retrying anyway")
    COUNTERS.bump("backend_init_resets")
    return True


def advance_with_recovery(
    make_engine,
    ckpt,
    *,
    engine=None,
    levels_per_chunk: int | None = None,
    max_level: int | None = None,
    save=None,
    max_restarts: int = 2,
    log=None,
):
    """Drive a checkpointed traversal to completion, surviving transient
    device/compile failures by rebuilding the engine and resuming from the
    last durable state.

    ``make_engine()`` must build a fresh engine over the same graph (the
    failure may have poisoned device buffers or the compile client);
    ``engine`` seeds the first attempt so callers reuse one they already
    built. ``save(ckpt)`` (optional) persists each chunk — the recovery
    point. Non-transient exceptions (wrong answers, OOM, truncation)
    propagate immediately; after ``max_restarts`` rebuilds the transient
    error propagates too. Returns ``(engine, ckpt, restarts)``.
    """
    if engine is None:
        engine = make_engine()
    restarts = 0
    while not ckpt.done and (max_level is None or ckpt.level < max_level):
        levels = levels_per_chunk
        if max_level is not None:
            room = max_level - ckpt.level
            levels = room if levels is None else min(levels, room)
        try:
            if _faults.ACTIVE is not None:
                # Chaos-harness injection site: a transient raised here is
                # handled by exactly the rebuild-and-resume path below —
                # the mechanism the ad-hoc per-test monkeypatches used to
                # approximate (tpu_bfs/faults.py).
                _faults.ACTIVE.hit("advance", level=ckpt.level)
            nxt = engine.advance(ckpt, levels=levels)
        except Exception as exc:  # noqa: BLE001 — gated by the classifier
            if restarts >= max_restarts or not is_transient_failure(exc):
                raise
            restarts += 1
            COUNTERS.bump("transient_retries")
            COUNTERS.bump("engine_rebuilds")
            if log is not None:
                log(
                    f"transient failure at level {ckpt.level} "
                    f"({type(exc).__name__}: {str(exc)[:200]}); rebuilding "
                    f"engine and resuming (restart {restarts}/{max_restarts})"
                )
            if reset_failed_backend_init(exc, log=log):
                time.sleep(BACKEND_INIT_RETRY_FLOOR_S)
            # Engine builds are compile-heavy too — the rebuild itself may
            # hit the same blip; keep it inside the restart budget.
            while True:
                try:
                    engine = make_engine()
                    break
                except Exception as exc2:  # noqa: BLE001
                    if restarts >= max_restarts or not is_transient_failure(exc2):
                        raise
                    restarts += 1
                    COUNTERS.bump("transient_retries")
                    COUNTERS.bump("engine_rebuilds")
                    if log is not None:
                        log(
                            f"transient failure rebuilding the engine "
                            f"({type(exc2).__name__}); retrying "
                            f"(restart {restarts}/{max_restarts})"
                        )
                    if reset_failed_backend_init(exc2, log=log):
                        time.sleep(BACKEND_INIT_RETRY_FLOOR_S)
            continue
        ckpt = nxt
        if save is not None:
            save(ckpt)
    return engine, ckpt, restarts
