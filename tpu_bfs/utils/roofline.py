"""Per-phase attribution of the hybrid MS-BFS level loop (roofline).

"Is it actually fast, or just faster than before?" — the flagship number
(62 GTEPS hmean on RMAT scale-21, BENCHMARKS.md) is one fused
``lax.while_loop``; this module breaks a real traversal into its phases and
prices each against the chip's HBM bandwidth, so the binding term is NAMED
and the next optimization is attributable instead of guesswork.

Method: step the REAL engine one level at a time, device-resident
(``engine._core_from`` with ``max_levels = level+1`` — the checkpoint API's
host round-trip would move ~2 GB/table per level at flagship scale and
drown the phases). On each level's live frontier, separately dispatch
jitted PHASE SLICES rebuilt from the same specs the fused loop was built
from (msbfs_hybrid.expand_spec / tile_spmm / the adaptive push body /
the claim+ripple state update), each timed with the scalar-read fence and
floor subtraction of utils/timing.run_timed. The slices re-run work the
fused loop runs once, so their sum normally EXCEEDS the fused level time;
the difference is XLA's fusion dividend and is reported, not hidden.

The byte model is analytic and fusion-agnostic: for each phase, the HBM
bytes its algorithm must move at least once (tables read/written, index
arrays, gathered rows). Achieved GB/s = bytes / measured time; the phase
with the largest share of attributed time is the binding term, and the
implied ceiling is the batch rate if every phase ran at peak HBM bandwidth
(v5e: ~819 GB/s) — the batched analog of BENCHMARKS.md's single-stream
latency-wall analysis.

Correctness guard: the stepping loop's level count must equal a plain
``engine.run``'s (same sources), proving the slices did not perturb the
traversal. Reference analog: the reference has no attribution at all —
its record is one wall-clock print per run (bfs.cu:624-626).

Works on CPU/interpret for tests (tiny graphs); meaningful numbers need
the chip (scripts/roofline.py).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tpu_bfs.algorithms._packed_common import make_expand
from tpu_bfs.algorithms.msbfs_hybrid import expand_spec
from tpu_bfs.algorithms.msbfs_packed import ripple_increment
from tpu_bfs.obs.engine_trace import trace_summary as _trace_summary
from tpu_bfs.ops.tile_spmm import TILE, tile_spmm
from tpu_bfs.utils.timing import run_timed

V5E_PEAK_GBS = 819.0  # HBM2 bandwidth of one v5e chip, vendor figure


def phase_fns(engine) -> dict:
    """Jitted phase slices of one hybrid level step.

    Keys (present when the engine has the phase): ``residual`` (bucketed
    ELL gathers + permutation back to rank0), ``dense`` (Pallas MXU tile
    pass), ``push`` (adaptive push body, gate-free), ``gate`` (the adaptive
    light-level decision inputs), ``hit`` (the full expansion exactly as
    the fused loop composes it, pull form), and the state update sliced
    as ``claim`` (hit & ~vis claim + visited OR + liveness) and
    ``ripple`` (bit-plane increment) — reported summed as 'state' in the
    attribution.
    """
    hg, w = engine.hg, engine.w
    act = hg.num_active
    out_rows = hg.vt * TILE
    # The residual slice runs THE SAME expansion tier as the fused loop
    # (ISSUE 16): a pallas-tier engine's attribution must time the fused
    # kernel, not the fori form it replaced. The engine's arrs already
    # carry the tier's tables (padded gt slabs on the pallas tier).
    expand_residual = make_expand(
        expand_spec(hg), w,
        impl=getattr(engine, "expand_impl", "xla"),
        interpret=engine.interpret,
    )
    fns = {}

    def residual(arrs, fw):
        return expand_residual(arrs, fw)[arrs["inv_perm_ext"]]

    fns["residual"] = jax.jit(residual)

    has_dense = hg.num_tiles > 0
    if has_dense:
        def dense(arrs, fw):
            return tile_spmm(
                arrs["row_start"], arrs["col_tile"], arrs["a_tiles"], fw,
                num_row_tiles=hg.vt, w=w, interpret=engine.interpret,
            )

        fns["dense"] = jax.jit(dense)

    def hit(arrs, fw):
        h = residual(arrs, fw)
        return h | dense(arrs, fw) if has_dense else h

    fns["hit"] = jax.jit(hit)

    if engine.adaptive_push is not None:
        row_cap, _deg_cap = engine.adaptive_push

        def gate(arrs, fw):
            rows_active = jnp.any(fw[:act] != 0, axis=1)
            nz = jnp.sum(rows_active.astype(jnp.int32))
            bad = jnp.any(rows_active & arrs["push_inelig"])
            return nz, bad

        fns["gate"] = jax.jit(gate)

        def push(arrs, fw):
            # The push body of _packed_common.make_adaptive_hit, without
            # the lax.cond gate (attribution wants the branch itself).
            rows_active = jnp.any(fw[:act] != 0, axis=1)
            nz = jnp.sum(rows_active.astype(jnp.int32))
            idx = jnp.where(rows_active, size=row_cap, fill_value=act)[0]
            pt = arrs["push_t"]

            def pbody(i, h):
                r = idx[i]
                nb = pt[r]
                return h.at[nb].set(h[nb] | fw[r][None, :])

            h = jax.lax.fori_loop(
                0, nz, pbody, jnp.zeros((out_rows, w), jnp.uint32)
            )
            return h.at[act].set(0)

        fns["push"] = jax.jit(push)

    # The state update is sliced in two so each dispatch's live set fits
    # next to the standing carry at flagship scale (claim's outputs can't
    # alias its inputs we still hold; ripple doubles the plane tables —
    # one fused state fn peaked ~4 extra tables and OOM'd the 16 GB chip
    # at scale 21 / w=256). Reported summed as 'state'.
    def claim(h, vis):
        nxt = h & ~vis
        return nxt, vis | nxt, jnp.any(nxt != 0)

    def ripple(planes, vis2):
        return ripple_increment(planes, ~vis2)

    fns["claim"] = jax.jit(claim)
    fns["ripple"] = jax.jit(ripple)
    return fns


def pallas_expand_bytes(engine, *, active_tiles: int | None = None) -> dict:
    """Per-kernel HBM bytes of ONE pallas-tier expansion level (ISSUE 16).

    One entry per kernel launch ('virtual', 'light0', ...), derived from
    the engine's padded gt slabs and priced by
    ``ops.ell_expand.ell_expand_hbm_bytes``: per computed 128-row tile,
    the index slab + k gathered frontier rows per row (+ the weight slab
    on min-plus kernels) + ONE output write. The VMEM-resident
    accumulator is what separates this from the fori tier's model
    (``phase_bytes``), which pays the accumulator round-trip on every
    bucket step — this dict is the bound the kernel is built to meet.

    Distributed engines hold per-shard gt stacks (leading axes); bytes
    count across shards. ``active_tiles`` (gated engines: unsettled
    GATE_TILE blocks this level) caps each light kernel's computed
    tiles; the heavy kernel is all-or-nothing, exactly like the gated
    program (gated-out tiles still pay their identity write). Returns
    ``{}`` when the engine runs the xla tier.
    """
    if getattr(engine, "expand_impl", "xla") != "pallas":
        return {}
    from tpu_bfs.ops.ell_expand import TILE as KTILE, ell_expand_hbm_bytes

    arrs = getattr(engine, "arrs", None) or {}
    w = engine.w
    out = {}
    for name in sorted(arrs):
        if not name.endswith("_gt"):
            continue
        base = name[: -len("_gt")]
        # Index slabs only: 'virtual' / 'light<i>'. Weight slabs
        # ('<base>_w'/'<base>_wl', sssp) ride their index kernel's
        # launch via the ``weighted`` flag below.
        if base != "virtual" and not (
            base.startswith("light") and "_" not in base
        ):
            continue
        t = arrs[name]
        k, pn = int(t.shape[-2]), int(t.shape[-1])
        shards = 1
        for d in t.shape[:-2]:
            shards *= int(d)
        if active_tiles is None:
            at = None
        elif base == "virtual":
            at = None if active_tiles > 0 else 0
        else:
            at = min(pn // KTILE, int(active_tiles))
        out[base] = shards * ell_expand_hbm_bytes(
            k, pn, w, active_tiles=at, weighted=f"{base}_w_gt" in arrs
        )
    return out


def phase_bytes(engine, *, nz_rows: int | None = None,
                active_tiles: int | None = None) -> dict:
    """Analytic HBM bytes per phase for ONE level (lower bounds: bytes the
    phase's algorithm must move at least once; XLA fusion can only reduce
    intermediate traffic below this for `state`, so achieved-GB/s figures
    derived from these are conservative for the expansion phases).

    ``nz_rows`` (active frontier rows) sizes the push phase. Without the
    pull gate, the pull phases are frontier-independent by construction
    (the whole table is scanned every level — that level-invariance was
    the roofline finding ISSUE 1 acted on). On a pull-gated engine,
    ``active_tiles`` (unsettled GATE_TILE row blocks this level) sizes the
    gated model instead: light-bucket gathers and the state pass scale
    with the active-tile count; the heavy section is all-or-nothing
    (counted fully while any tile is active, zero at 0); the permutation
    gather and the next-frontier zero-init stay full-table (the compiled
    program still writes them full-height), and the settled-mask read adds
    one table scan — the model bills the gate's own overhead so the gated
    entry stays honest.

    Distributed MS engines (``_gather_p > 1``) add an ``exchange`` entry —
    per-level WIRE bytes, not HBM: the dense slab gather and the sliced
    ring rotation both move (P-1) x [rows/P, w] u32 per chip per level
    (dist_msbfs_hybrid; the sparse row-gather rungs move less — and the
    ISSUE 7 delta-encoded id stream less again; this is the dense
    ceiling, the per-branch prices live in
    collectives.sparse_rows_wire_bytes_per_level and the walk's trace
    rows attribute the branch each level actually took). The packed MS
    wire format already carries one bit per (vertex, lane), so ISSUE 5's
    ``wire_pack`` does not change this entry; their HBM phases are the
    single-chip model's, per chip, and are not re-derived here (``hg``
    is absent on those engines).
    """
    from tpu_bfs.parallel.collectives import dense_rows_wire_bytes

    hg, w = getattr(engine, "hg", None), engine.w
    out = {}
    p = int(getattr(engine, "_gather_p", 1))
    if p > 1:
        out["exchange"] = dense_rows_wire_bytes(p, engine._gather_rows_loc, w)
    if hg is None:
        return out
    rows = hg.vt * TILE
    tb = rows * w * 4  # one [rows, w] u32 table
    gated = bool(getattr(engine, "pull_gate", False)) and active_tiles is not None
    at_rows = min(int(active_tiles or 0) * TILE, rows) if gated else rows
    pal = pallas_expand_bytes(
        engine, active_tiles=active_tiles if gated else None
    )
    if pal:
        # Pallas tier (ISSUE 16): per-kernel attribution — the
        # VMEM-resident accumulator drops the fori tier's per-step
        # accumulator round-trip, so the residual bound shrinks to the
        # kernel model. The heavy fold pyramid + pick gather still run
        # in jnp after the kernel.
        res = sum(pal.values())
        if hg.res_heavy and (not gated or at_rows > 0):
            res += 4 * hg.res_num_virtual * w * 4 + hg.res_heavy * w * 4
    else:
        # residual: per light bucket, k fori steps each gathering n rows
        # (n*w*4 read) and accumulating (acc read+write) + index table;
        # the virtual/heavy bucket adds its fold pyramid and pick
        # gathers.
        res = 0
        if hg.res_heavy and (not gated or at_rows > 0):
            m = hg.res_virtual.idx.shape[0]  # rows per virtual gather
            res += hg.kcap * (3 * hg.res_num_virtual * w * 4) + hg.kcap * m * 4
            # fold pyramid: halving read+write chain ~ 2 * 2*num_virtual
            # rows, then the heavy_pick gather back out.
            res += 4 * hg.res_num_virtual * w * 4 + hg.res_heavy * w * 4
        for b in hg.res_light:
            n, k = b.idx.shape
            ne = min(n, at_rows) if gated else n
            res += k * (3 * ne * w * 4) + ne * k * 4
    # permutation back to rank0: read bucket rows + write the rank0 table.
    res += 2 * tb
    out["residual"] = res
    if hg.num_tiles:
        # a_tiles streamed once; each (row,col) tile production reads a
        # 128-row frontier slab column; output written once per row tile.
        # (Ungated even on gated engines — see msbfs_hybrid._make_core.)
        out["dense"] = hg.a_tiles.nbytes + hg.num_tiles * TILE * w * 4 + tb
    if engine.adaptive_push is not None:
        deg_cap = engine.adaptive_push[1]
        nz = int(nz_rows or 0)
        # zero-init of the hit table + per active row: its frontier word
        # row read + deg_cap neighbor rows read-modify-write.
        out["push"] = tb + nz * (1 + 2 * deg_cap) * w * 4
    if gated:
        # Gated state: full-table settled-mask read + next-frontier
        # zero-init, then claim/visited/ripple traffic on active tiles.
        out["state"] = 2 * tb + (
            (3 + 2 * engine.num_planes) * at_rows * w * 4
        )
    else:
        # claim reads hit+vis, writes vis and nxt; ripple reads+writes
        # planes.
        out["state"] = (4 + 2 * engine.num_planes) * tb
    return out


@dataclasses.dataclass
class LevelAttribution:
    level: int
    frontier_rows: int  # active rows entering the level
    took: str  # 'push' (adaptive light level) or 'pull'
    t_full_s: float  # the real fused one-level step
    phases_s: dict  # phase -> seconds (standalone slice)
    bytes_model: dict  # phase -> analytic HBM bytes
    # Unsettled GATE_TILE blocks entering the level (pull-gated engines
    # only; sizes the gated byte model). None when the engine is ungated.
    active_tiles: int | None = None
    # Exchange branch this level's step recorded (distributed MS engines
    # stepping through _core_from — the diff of the chunk-chained
    # per-branch counters; None when unobserved, e.g. the donating TPU
    # step path, which bypasses the recording).
    exchange_branch: int | None = None


def roofline_hybrid(engine, sources, *, peak_gbs: float = V5E_PEAK_GBS,
                    measured_gteps: float | None = None,
                    log=None) -> dict:
    """Attribute a real traversal of ``sources`` level by level.

    Returns a JSON-ready report: per-level attribution, per-phase totals
    with shares and achieved GB/s, the fusion dividend, the named binding
    term, and the peak-bandwidth ceiling implied by the byte model (scaled
    from ``measured_gteps`` when given — pass the timed batch's figure so
    the ceiling is anchored to the same run protocol)."""
    fns = phase_fns(engine)
    arrs = engine.arrs
    sources = np.asarray(sources)
    # Pull-gated engines: refine the gate's lane mask to this batch (the
    # all-ones default is safe but gates nothing until every lane settles).
    # The phase SLICES stay the ungated forms — for a gated engine the
    # per-level gap between the slice sum and t_full then measures the
    # gate's win directly; the byte model switches to the gated entries.
    note = getattr(engine, "_note_batch_sources", None)
    if note is not None:
        note(sources)
    fw = engine._seed_dev(sources)
    # vis must be a DISTINCT buffer: the donating step would otherwise
    # donate the same seed buffer through two donated parameters, which
    # PJRT rejects at execute time.
    vis = jnp.copy(fw)
    planes = tuple(jnp.zeros_like(fw) for _ in range(engine.num_planes))
    level, alive = 0, True
    cap = engine.max_levels_cap
    row_cap = engine.adaptive_push[0] if engine.adaptive_push else None
    levels: list[LevelAttribution] = []

    def try_timed(call, warm):
        """Phase timing with an OOM seatbelt: a slice whose live set
        doesn't fit next to the standing carry reports None (partial
        attribution beats losing the whole report), anything else
        propagates."""
        try:
            return run_timed(call, warm=warm)
        except Exception as exc:  # noqa: BLE001 — OOM-only degrade
            if "RESOURCE_EXHAUSTED" not in str(exc):
                raise
            if log is not None:
                log(f"phase slice OOM'd; reporting None ({str(exc)[:120]})")
            return None, None

    # One-level fused step. On TPU the carry buffers are DONATED so the
    # step's output can alias them — without donation the old and new
    # carries are simultaneously live (the standing 6 tables twice over)
    # and the stepping OOMs at flagship scale where engine.run fits.
    # Donated inputs are consumed per call, so the usual warm-by-running
    # is impossible; the compile is absorbed via AOT lower().compile()
    # (which executes nothing) and the Compiled object is called once per
    # level.
    raw_step = getattr(engine._core_from, "__wrapped__", None)
    donating = raw_step is not None and jax.default_backend() == "tpu"
    step_fn = (
        jax.jit(raw_step, donate_argnums=(1, 2, 3))
        if donating else engine._core_from
    )
    compiled_step = None

    count_rows = jax.jit(
        lambda f: jnp.sum(jnp.any(f[: engine._act] != 0, axis=1)
                          .astype(jnp.int32))
    )
    count_tiles = None
    if getattr(engine, "pull_gate", False):
        from tpu_bfs.algorithms._packed_common import (
            GATE_TILE,
            row_unsettled,
        )

        nt_tiles = engine._table_rows // GATE_TILE

        @jax.jit
        def count_tiles(v, lane_mask):
            need = row_unsettled(v, engine._act, lane_mask)
            blk = jnp.any(
                need[: nt_tiles * GATE_TILE].reshape(nt_tiles, GATE_TILE),
                axis=1,
            )
            return jnp.sum(blk.astype(jnp.int32))

    # Each slice warms on its own FIRST dispatch, not at level 0: the push
    # slice no longer dispatches on pull levels (ADVICE r5 — timing it
    # there ran a row_cap-truncated index table through an nz-trip fori,
    # a million-iteration clamped scatter that could blow the pstage
    # timeout), so its first dispatch can land at any level.
    warmed: set[str] = set()
    # Chunk-chained exchange counters of the previous step (per-level
    # branch attribution below diffs against them).
    prev_counts_walk = None

    def timed_slice(name, call):
        out, t = try_timed(call, name not in warmed)
        warmed.add(name)
        return out, t

    while alive and level < cap:
        warm = level == 0
        nz = int(count_rows(fw))
        at = (
            int(count_tiles(vis, engine._lane_mask_dev))
            if count_tiles is not None
            else None
        )
        took = "pull"
        if "gate" in fns:
            g_nz, g_bad = fns["gate"](arrs, fw)
            if int(g_nz) <= row_cap and not bool(g_bad):
                took = "push"
        phases = {}
        for name in ("residual", "dense", "push"):
            if name not in fns:
                continue
            if name == "push" and took != "push":
                # The fused loop does not run push this level; dispatching
                # the gate-free slice anyway would time an out-of-contract
                # input (see the warmed-set note above).
                continue
            out, t = timed_slice(name, partial(fns[name], arrs, fw))
            del out  # free the [rows, w] hit before the next dispatch
            phases[name] = t
        # State = claim + ripple, timed separately (see phase_fns) on a
        # freshly materialized full hit. The hit materialization itself
        # is the largest slice intermediate — same OOM seatbelt.
        try:
            h = fns["hit"](arrs, fw)
            jax.block_until_ready(h)
        except Exception as exc:  # noqa: BLE001 — OOM-only degrade
            if "RESOURCE_EXHAUSTED" not in str(exc):
                raise
            h = None
            if log is not None:
                log(f"hit materialization OOM'd ({str(exc)[:120]})")
        if h is None:
            cl, t_claim = None, None
        else:
            cl, t_claim = timed_slice("claim", partial(fns["claim"], h, vis))
            del h
        if cl is None:
            phases["state"] = None
        else:
            _nxt, vis2p, _ = cl
            del cl, _nxt
            out, t_rip = timed_slice(
                "ripple", partial(fns["ripple"], planes, vis2p)
            )
            del out, vis2p
            phases["state"] = (
                None if t_rip is None else t_claim + t_rip
            )

        step_args = (
            arrs, fw, vis, planes, jnp.int32(level), jnp.int32(level + 1)
        )
        if donating:
            if compiled_step is None:
                compiled_step = step_fn.lower(*step_args).compile()
            step, step_warm = partial(compiled_step, *step_args), False
        else:
            step, step_warm = partial(step_fn, *step_args), warm
        (fw2, vis2, planes2, lvl2, alive2), t_full = run_timed(
            step, warm=step_warm
        )
        # Which exchange branch did this one-level step take? Without a
        # chain nonce each step RESTARTS the per-branch counters
        # (collectives.chained_prev_counts), so they are usually a
        # one-level one-hot; a chained engine instead accumulates, and
        # the level's branch is the diff against the previous step's
        # counters. The donating TPU path calls the raw core and records
        # nothing — branch stays None.
        step_branch = None
        counts_now = getattr(engine, "last_exchange_level_counts", None)
        if not donating and counts_now is not None:
            counts_now = np.asarray(counts_now)
            if counts_now.sum() == 1:
                step_branch = int(np.argmax(counts_now))
            elif (
                prev_counts_walk is not None
                and prev_counts_walk.shape == counts_now.shape
            ):
                hot = np.flatnonzero(counts_now - prev_counts_walk)
                if len(hot) == 1 and counts_now[hot[0]] > prev_counts_walk[hot[0]]:
                    step_branch = int(hot[0])
            prev_counts_walk = counts_now
        levels.append(LevelAttribution(
            level=level, frontier_rows=nz, took=took, t_full_s=t_full,
            phases_s=phases,
            bytes_model=phase_bytes(engine, nz_rows=nz, active_tiles=at),
            active_tiles=at,
            exchange_branch=step_branch,
        ))
        if log is not None:
            gate_msg = "" if at is None else f"active_tiles={at} "
            log(f"level {level}: rows={nz} took={took} {gate_msg}"
                f"full={t_full*1e3:.1f}ms " + " ".join(
                    f"{k}={v*1e3:.1f}ms" if v is not None else f"{k}=OOM"
                    for k, v in phases.items()))
        fw, vis, planes = fw2, vis2, planes2
        level, alive = int(lvl2), bool(alive2)

    # ---- aggregate ----
    # Attributed time: the phases the fused loop actually runs per level
    # (push levels skip residual+dense; pull levels skip push) + state.
    tot_attr: dict[str, float] = {}
    tot_bytes: dict[str, float] = {}
    t_full_sum = 0.0
    unmeasured = 0  # phase slices that OOM'd next to the standing carry
    for la in levels:
        t_full_sum += la.t_full_s
        names = (["push"] if la.took == "push" else
                 [n for n in ("residual", "dense") if n in la.phases_s])
        for n in names + ["state"]:
            t = la.phases_s.get(n)
            if t is None:
                unmeasured += 1
                continue
            tot_attr[n] = tot_attr.get(n, 0.0) + t
            tot_bytes[n] = tot_bytes.get(n, 0.0) + la.bytes_model.get(n, 0)
    attr_sum = sum(tot_attr.values())
    # Fold the walk into the unified engine-trace contract (ISSUE 6):
    # the roofline drives the level loop one step at a time, so it
    # observes per-level frontier rows and direction directly — richer
    # than the fused loop's own recording. gated_tiles converts the
    # gate's input (active tiles) into the trace's skip count.
    trace_rows = []
    exch_bytes = getattr(engine, "wire_bytes_per_level", None)
    exch_per = [float(x) for x in exch_bytes()] if exch_bytes is not None else None
    exch_each = (
        exch_per[0] if exch_per is not None and len(exch_per) == 1 else None
    )
    # Per-branch labels (cap rungs, ISSUE 7 delta widths) for engines
    # that publish them; the per-level branch came from the step diffs.
    label_hook = getattr(engine, "exchange_branch_labels", None)
    exch_labels = label_hook() if callable(label_hook) else None
    for la in levels:
        gated_tiles = None
        if la.active_tiles is not None:
            from tpu_bfs.algorithms._packed_common import GATE_TILE

            total_tiles = engine._table_rows // GATE_TILE
            gated_tiles = max(total_tiles - la.active_tiles, 0)
        b = la.exchange_branch
        label = (
            exch_labels[b]
            if exch_labels is not None and b is not None
            and b < len(exch_labels) else None
        )
        wire = exch_each
        if b is not None and exch_per is not None and b < len(exch_per):
            wire = exch_per[b]
        trace_rows.append({
            "level": la.level,
            "frontier": la.frontier_rows,
            "direction": (
                "push" if la.took == "push"
                else "pull-gated" if la.active_tiles is not None else "pull"
            ),
            "gated_tiles": gated_tiles,
            "exchange": label,
            "wire_bytes": wire,
        })
    engine.last_run_trace = trace_rows
    # Full degradation (every slice OOM'd) still emits the partial report
    # — per-level t_full and the unmeasured count are real data.
    binding = max(tot_attr, key=tot_attr.get) if tot_attr else None
    total_bytes = sum(tot_bytes.values())
    report = {
        "num_levels": len(levels),
        # Gated engines: the byte model uses the gated entries and the
        # slices stay ungated, so per-level (slice sum - t_full) includes
        # the gate's win; levels[i].active_tiles records the gate's input.
        "pull_gate": bool(getattr(engine, "pull_gate", False)),
        "levels": [dataclasses.asdict(la) for la in levels],
        "t_full_sum_s": t_full_sum,
        "t_attributed_sum_s": attr_sum,
        # slices re-run what the fused loop fuses; the gap is XLA's win.
        "fusion_dividend_s": attr_sum - t_full_sum,
        "phase_share": {n: t / attr_sum for n, t in tot_attr.items()},
        "phase_achieved_gbs": {
            n: (tot_bytes[n] / 1e9) / t if t > 0 else None
            for n, t in tot_attr.items()
        },
        "binding_term": binding,
        "unmeasured_phase_slices": unmeasured,
        # Compact engine-trace form (obs/engine_trace.trace_summary): the
        # same keys bench.py's verdict carries, derived from this walk.
        "trace_summary": _trace_summary(trace_rows, engine),
        "peak_gbs": peak_gbs,
        "hbm_bytes_total": total_bytes,
        # time the whole byte model would take at peak bandwidth.
        "t_at_peak_bw_s": total_bytes / (peak_gbs * 1e9),
    }
    # Expansion-tier attribution (ISSUE 16): which tier ran, and — on the
    # pallas tier — the per-kernel VMEM-resident byte bound of one
    # ungated level with its time at peak bandwidth (the BLEST-style
    # floor the fused kernel chases; compare against the residual
    # phase's achieved figure above).
    report["expand_impl"] = getattr(engine, "expand_impl", "xla")
    pal = pallas_expand_bytes(engine)
    if pal:
        report["expand_kernel_bytes"] = {
            **{k: int(v) for k, v in pal.items()},
            "level_total": int(sum(pal.values())),
        }
        report["expand_kernel_t_at_peak_bw_s"] = (
            sum(pal.values()) / (peak_gbs * 1e9)
        )
    if measured_gteps is not None:
        # The fused batch measured `measured_gteps`; if every attributed
        # phase ran at peak HBM bandwidth, the same byte model implies:
        report["measured_gteps"] = measured_gteps
        report["ceiling_gteps_at_peak_bw"] = (
            measured_gteps * t_full_sum / report["t_at_peak_bw_s"]
            if report["t_at_peak_bw_s"] > 0 else None
        )
    return report
