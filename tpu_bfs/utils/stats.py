"""Per-level BFS statistics.

The reference's only observability is printf: commented-out debug kernel twins
(bfs.cu:53-96, 168-189), a raised printf FIFO limit (bfs.cu:486-490), and
wall-clock prints (bfs.cu:624-626). Here the level structure is recovered
exactly from the final distance array — frontier-size-by-level is its
histogram, and edges scanned per level is the degree-weighted histogram — so
stats cost nothing in the device loop and are available for every engine.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

from tpu_bfs.graph.csr import INF_DIST


@dataclasses.dataclass(frozen=True)
class LevelStats:
    """Per-level traversal statistics for one BFS run."""

    frontier_size: np.ndarray  # [L+1] vertices discovered at each level
    edges_scanned: np.ndarray  # [L+1] sum of out-degrees of each level's frontier
    reached: int
    unreached: int
    # Pull gate (ISSUE 1): blocks/tiles the gate skipped expanding each
    # level (engine.last_gate_level_counts, trimmed to the level count).
    # None on ungated runs — the key is then absent from json_lines.
    gated_tiles: np.ndarray | None = None

    @property
    def num_levels(self) -> int:
        return len(self.frontier_size) - 1

    def json_lines(self) -> list[str]:
        """One JSON object per level (the --stats output format)."""
        lines = []
        for lvl in range(len(self.frontier_size)):
            entry = {
                "level": lvl,
                "frontier": int(self.frontier_size[lvl]),
                "edges_scanned": int(self.edges_scanned[lvl]),
            }
            if self.gated_tiles is not None:
                entry["gated_tiles"] = int(self.gated_tiles[lvl])
            lines.append(json.dumps(entry))
        return lines


def level_stats(distance: np.ndarray, degrees: np.ndarray,
                gated_tiles: np.ndarray | None = None) -> LevelStats:
    """Compute LevelStats from a distance array (int32, INF_DIST = unreached).

    ``edges_scanned[l]`` is the work a level-synchronous sweep performs
    expanding level l — the degree sum of that level's frontier.
    ``gated_tiles`` (a pull-gated engine's ``last_gate_level_counts``,
    trimmed by the caller to the BATCH's level count) indexes the level
    being EXPANDED, matching ``edges_scanned``'s convention. When the
    batch ran deeper than THIS distance array's eccentricity (a
    multi-source batch where other lanes kept claiming — exactly the
    tail levels the gate targets), the output extends to the counts'
    length with zero frontier/edges rows rather than silently dropping
    the deepest counts. NB the counters' unit is engine-specific:
    skipped 128-row blocks on the single-chip/gather engines, skipped
    per-chip contribution computes (<= P per level) on the ring-sliced
    distributed layout.
    """
    distance = np.asarray(distance)
    reached_mask = distance != INF_DIST
    reached = distance[reached_mask]
    if reached.size == 0:
        return LevelStats(
            frontier_size=np.zeros(1, np.int64),
            edges_scanned=np.zeros(1, np.int64),
            reached=0,
            unreached=int((~reached_mask).sum()),
            gated_tiles=None if gated_tiles is None else np.zeros(1, np.int64),
        )
    n_levels = int(reached.max())
    n_out = n_levels + 1
    gt = None
    if gated_tiles is not None:
        src = np.asarray(gated_tiles, np.int64)
        n_out = max(n_out, len(src))
        gt = np.zeros(n_out, np.int64)
        gt[: len(src)] = src
    frontier = np.bincount(reached, minlength=n_out).astype(np.int64)
    edges = np.bincount(
        reached, weights=np.asarray(degrees, np.float64)[reached_mask],
        minlength=n_out,
    ).astype(np.int64)
    return LevelStats(
        frontier_size=frontier,
        edges_scanned=edges,
        reached=int(reached_mask.sum()),
        unreached=int((~reached_mask).sum()),
        gated_tiles=gt,
    )


def recovery_stats_line() -> str | None:
    """The --stats trailer surfacing the process's recovery counters
    (utils/recovery.COUNTERS): one ``{"recovery": {...}}`` JSON line when
    any retry/rebuild/OOM-degrade fired this process, None otherwise — a
    run that silently survived infrastructure trouble must say so in the
    same place its level stats land (round-6 satellite: recovery used to
    retry with no post-hoc trace)."""
    from tpu_bfs.utils.recovery import COUNTERS

    if not COUNTERS.any():
        return None
    return json.dumps({"recovery": COUNTERS.as_dict()})
