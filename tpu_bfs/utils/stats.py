"""Per-level BFS statistics.

The reference's only observability is printf: commented-out debug kernel twins
(bfs.cu:53-96, 168-189), a raised printf FIFO limit (bfs.cu:486-490), and
wall-clock prints (bfs.cu:624-626). Here the level structure is recovered
exactly from the final distance array — frontier-size-by-level is its
histogram, and edges scanned per level is the degree-weighted histogram — so
stats cost nothing in the device loop and are available for every engine.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

from tpu_bfs.graph.csr import INF_DIST


@dataclasses.dataclass(frozen=True)
class LevelStats:
    """Per-level traversal statistics for one BFS run."""

    frontier_size: np.ndarray  # [L+1] vertices discovered at each level
    edges_scanned: np.ndarray  # [L+1] sum of out-degrees of each level's frontier
    reached: int
    unreached: int

    @property
    def num_levels(self) -> int:
        return len(self.frontier_size) - 1

    def json_lines(self) -> list[str]:
        """One JSON object per level (the --stats output format)."""
        return [
            json.dumps(
                {
                    "level": lvl,
                    "frontier": int(self.frontier_size[lvl]),
                    "edges_scanned": int(self.edges_scanned[lvl]),
                }
            )
            for lvl in range(len(self.frontier_size))
        ]


def level_stats(distance: np.ndarray, degrees: np.ndarray) -> LevelStats:
    """Compute LevelStats from a distance array (int32, INF_DIST = unreached).

    ``edges_scanned[l]`` is the work a level-synchronous sweep performs
    expanding level l — the degree sum of that level's frontier.
    """
    distance = np.asarray(distance)
    reached_mask = distance != INF_DIST
    reached = distance[reached_mask]
    if reached.size == 0:
        return LevelStats(
            frontier_size=np.zeros(1, np.int64),
            edges_scanned=np.zeros(1, np.int64),
            reached=0,
            unreached=int((~reached_mask).sum()),
        )
    n_levels = int(reached.max())
    frontier = np.bincount(reached, minlength=n_levels + 1).astype(np.int64)
    edges = np.bincount(
        reached, weights=np.asarray(degrees, np.float64)[reached_mask],
        minlength=n_levels + 1,
    ).astype(np.int64)
    return LevelStats(
        frontier_size=frontier,
        edges_scanned=edges,
        reached=int(reached_mask.sum()),
        unreached=int((~reached_mask).sum()),
    )
