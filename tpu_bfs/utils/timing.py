"""Device-timing helper shared by the BFS engines.

The reference times with std::chrono around each run (bfs.cu:624-626) and has
no JIT to exclude; here the first execution compiles, so engines warm once per
compiled shape before timing.
"""

from __future__ import annotations

import time

import jax


def run_timed(call, *, warm: bool):
    """Execute ``call`` and return (result, elapsed_seconds).

    When ``warm`` is true, one untimed execution runs first (absorbing
    compilation); the timed execution blocks until device completion.
    """
    if warm:
        jax.block_until_ready(call())
    t0 = time.perf_counter()
    out = call()
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0
