"""Device-timing helpers shared by the BFS engines and measurement scripts.

The reference times with std::chrono around each run (bfs.cu:624-626) and has
no JIT to exclude; here the first execution compiles, so engines warm once per
compiled shape before timing.
"""

from __future__ import annotations

import sys
import time

import jax
import numpy as np


def fence(out, *, warn: bool = False) -> float:
    """Completion fence; returns seconds spent waiting.

    ``block_until_ready`` alone proved unreliable as a fence on the axon
    remote platform (round 4: the first on-chip width-probe run "finished"
    a 2 GB gather chain in 36 µs — implied 56-213 TB/s on one v5e chip). A
    host read of an element *derived from* the output cannot return before
    the producing computation has run — the same discipline as the packed
    engines' ``int(levels)`` sync (_packed_common.py). One element, so the
    extra transfer is negligible against any timed run.

    With ``warn=True`` (measurement scripts), prints a stderr diagnostic
    when the scalar read did the real wait — the detector for the
    early-return bug recurring. Threshold 0.5 s: the first fence also
    compiles the one-element index op (~0.1 s), which is not a symptom.
    """
    t0 = time.perf_counter()
    jax.block_until_ready(out)
    t_block = time.perf_counter() - t0
    # EVERY non-empty device-array leaf gets a read (ADVICE r4: a pytree
    # of independently-dispatched results — run_timed's call() may return
    # a tuple of separate jitted outputs — is only fenced if each
    # dispatch's output is read; the first leaf alone left the later ones
    # covered solely by block_until_ready, the primitive this fence exists
    # to distrust). Python scalars are host values already and empty
    # arrays have no element to read.
    for leaf in jax.tree_util.tree_leaves(out):
        if not (hasattr(leaf, "ndim") and getattr(leaf, "size", 0)):
            continue
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            # Sharded output: read one element from EVERY shard — element
            # 0 alone only forces the device owning it, and per-device
            # work dispatched after the final collective elsewhere could
            # still be in flight.
            for s in shards:
                d = s.data
                np.asarray(d[(0,) * d.ndim])
        else:
            np.asarray(leaf[(0,) * leaf.ndim])
    t_read = time.perf_counter() - t0 - t_block
    if warn and t_read > max(0.5, 10 * t_block):
        print(
            f"WARNING: block_until_ready returned early (waited "
            f"{t_block:.6f}s); the scalar-read fence did the real wait "
            f"({t_read:.6f}s)",
            file=sys.stderr,
            flush=True,
        )
    return t_block + t_read


def run_timed(call, *, warm: bool):
    """Execute ``call`` and return (result, elapsed_seconds).

    When ``warm`` is true, one untimed execution runs first (absorbing
    compilation); the timed execution blocks until device completion. The
    fence's fixed epilogue (dispatch + host round-trip of the element
    reads — ~0.1 s over the axon tunnel, µs locally) is measured by a
    second fence on the already-materialized output and subtracted, so
    per-run figures don't carry a flat host-latency bias (the same
    correction scripts/width_probe.py applies).
    """
    if warm:
        fence(call())
    t0 = time.perf_counter()
    out = call()
    fence(out)
    t1 = time.perf_counter()
    raw = t1 - t0
    floor = fence(out)  # output is ready: pure epilogue cost
    corrected = raw - floor
    # Floor-dominated measurements (ADVICE r4) must not land unannotated:
    # - floor >= raw (tunnel jitter overshot the epilogue sample): the old
    #   1e-9 clamp turned that into an absurdly inflated rate. Report the
    #   UNCORRECTED time instead — a conservative overestimate, so derived
    #   rates err low — and say so.
    # - 0 < corrected < floor/10: the duration is below the correction's
    #   resolution (epilogue jitter is a meaningful fraction of it). The
    #   corrected value is still the best unbiased estimate (subtracting a
    #   ~0.1 s tunnel epilogue from a ~0.11 s raw is exactly this helper's
    #   job — the roofline's per-phase slices live here), so keep it, but
    #   annotate on stderr.
    if corrected <= 0:
        print(
            f"WARNING: fence epilogue ({floor:.4f}s) >= raw elapsed "
            f"({raw:.4f}s); floor-dominated measurement — reporting the "
            f"uncorrected time",
            file=sys.stderr,
            flush=True,
        )
        return out, max(raw, 1e-9)
    if corrected < floor / 10:
        print(
            f"NOTE: corrected elapsed {corrected:.5f}s is <10% of the "
            f"fence epilogue ({floor:.4f}s); below the floor-correction's "
            f"resolution — treat derived rates as +/- the epilogue jitter",
            file=sys.stderr,
            flush=True,
        )
    # Epsilon clamp, not 0.0: downstream TEPS math divides by elapsed (a
    # zero would turn the result's teps into None and crash its callers);
    # 1e-9 s matches width_probe's clamp.
    return out, max(corrected, 1e-9)
