"""Device-timing helpers shared by the BFS engines and measurement scripts.

The reference times with std::chrono around each run (bfs.cu:624-626) and has
no JIT to exclude; here the first execution compiles, so engines warm once per
compiled shape before timing.
"""

from __future__ import annotations

import sys
import time

import jax
import numpy as np


def fence(out, *, warn: bool = False) -> float:
    """Completion fence; returns seconds spent waiting.

    ``block_until_ready`` alone proved unreliable as a fence on the axon
    remote platform (round 4: the first on-chip width-probe run "finished"
    a 2 GB gather chain in 36 µs — implied 56-213 TB/s on one v5e chip). A
    host read of an element *derived from* the output cannot return before
    the producing computation has run — the same discipline as the packed
    engines' ``int(levels)`` sync (_packed_common.py). One element, so the
    extra transfer is negligible against any timed run.

    With ``warn=True`` (measurement scripts), prints a stderr diagnostic
    when the scalar read did the real wait — the detector for the
    early-return bug recurring. Threshold 0.5 s: the first fence also
    compiles the one-element index op (~0.1 s), which is not a symptom.
    """
    t0 = time.perf_counter()
    jax.block_until_ready(out)
    t_block = time.perf_counter() - t0
    # First leaf that is a non-empty device array; Python scalars are host
    # values already and empty arrays have no element to read.
    leaf = next(
        (l for l in jax.tree_util.tree_leaves(out)
         if hasattr(l, "ndim") and getattr(l, "size", 0)),
        None,
    )
    if leaf is not None:
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            # Sharded output: read one element from EVERY shard — element
            # 0 alone only forces the device owning it, and per-device
            # work dispatched after the final collective elsewhere could
            # still be in flight.
            for s in shards:
                d = s.data
                np.asarray(d[(0,) * d.ndim])
        else:
            np.asarray(leaf[(0,) * leaf.ndim])
    t_read = time.perf_counter() - t0 - t_block
    if warn and t_read > max(0.5, 10 * t_block):
        print(
            f"WARNING: block_until_ready returned early (waited "
            f"{t_block:.6f}s); the scalar-read fence did the real wait "
            f"({t_read:.6f}s)",
            file=sys.stderr,
            flush=True,
        )
    return t_block + t_read


def run_timed(call, *, warm: bool):
    """Execute ``call`` and return (result, elapsed_seconds).

    When ``warm`` is true, one untimed execution runs first (absorbing
    compilation); the timed execution blocks until device completion. The
    fence's fixed epilogue (dispatch + host round-trip of the element
    reads — ~0.1 s over the axon tunnel, µs locally) is measured by a
    second fence on the already-materialized output and subtracted, so
    per-run figures don't carry a flat host-latency bias (the same
    correction scripts/width_probe.py applies).
    """
    if warm:
        fence(call())
    t0 = time.perf_counter()
    out = call()
    fence(out)
    t1 = time.perf_counter()
    floor = fence(out)  # output is ready: pure epilogue cost
    # Epsilon clamp, not 0.0: downstream TEPS math divides by elapsed (a
    # zero would turn the result's teps into None and crash its callers);
    # 1e-9 s matches width_probe's clamp.
    return out, max(t1 - t0 - floor, 1e-9)
