"""Virtual-device bootstrap: make JAX expose >= n devices on machines with
fewer real accelerators by forcing n virtual XLA CPU devices.

This is how the framework tests multi-chip behavior without a pod — the
capability the reference lacks entirely (its 2-node MPI path,
/root/reference/bfs_mpi.cu:549-643, cannot be exercised without two real
nodes). One copy of the recipe, shared by ``tests/conftest.py`` and
``__graft_entry__.dryrun_multichip``.

The mechanics are delicate because XLA parses ``XLA_FLAGS`` once, at the
first client creation of *any* platform in the process:

- If no backend has been initialized yet, patching ``os.environ`` and
  updating ``jax_platforms`` is sufficient (and cheap — the real-accelerator
  plugin is never touched).
- If a backend was initialized but the flag was already in the environment
  (e.g. the axon TPU plugin probed first), dropping the backend cache makes
  the next CPU client honor the already-parsed flag.
- If the first client was created *before* the flag entered the environment,
  the parsed flag state is stale and nothing in-process can fix it; we raise
  with the exact external recipe instead of letting an undersized mesh make
  distributed code pass vacuously (the reference's own validation sin,
  bfs_mpi.cu:844-846).
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def _patch_flags(n: int) -> None:
    """Ensure XLA_FLAGS requests at least n host-platform devices."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_FLAG}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = f"{flags} {_FLAG}={n}".strip()
    elif int(m.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(m.group(0), f"{_FLAG}={n}")


def ensure_virtual_devices(n: int, *, prefer_real: bool = False) -> None:
    """Make ``jax.devices()`` return >= n devices, virtualizing on CPU.

    With ``prefer_real=True``, an already-sufficient real-device fleet is
    left untouched (the flag append is still done first — harmless, and it
    must precede the device probe to survive in the fallback case).
    Otherwise, or when real devices are too few, the CPU platform is forced
    with n virtual devices. Raises RuntimeError with the external recipe if
    the process consumed XLA_FLAGS before this call.
    """
    _patch_flags(n)
    import jax

    if not prefer_real:
        # Pre-init this is decisive; post-init it is silently ignored and
        # the clear_backends path below takes over.
        jax.config.update("jax_platforms", "cpu")
    if jax.device_count() >= n and (
        prefer_real or jax.devices()[0].platform == "cpu"
    ):
        return

    import jax.extend.backend as jeb

    jax.config.update("jax_platforms", "cpu")
    jeb.clear_backends()
    if jax.device_count() < n or jax.devices()[0].platform != "cpu":
        raise RuntimeError(
            f"could not bootstrap {n} virtual CPU devices (got "
            f"{jax.devices()}): XLA_FLAGS was consumed before "
            f"ensure_virtual_devices({n}) ran. Call it before any JAX "
            f"backend use, or launch with PALLAS_AXON_POOL_IPS= "
            f"JAX_PLATFORMS=cpu XLA_FLAGS={_FLAG}={n}."
        )
