"""Empirical cross-check of the modeled wire-byte accounting against XLA.

Every wire-byte figure the framework prints is modeled (a formula over
branch counts — collectives.py labels them so), because per-level hardware
byte counters don't exist on the CPU mesh and an xprof capture needs the
real chip. This module retires the "trust the formula" caveat a different
way: it parses the COMPILED program's collective instructions (HLO on the
8-virtual-device CPU mesh — the same program XLA runs on TPU, modulo
backend lowering) and re-derives the per-level bytes from the collectives'
own operand shapes. Agreement means the formulas describe what the
compiler actually emits, not what we hoped it would emit.

Conventions (ring collectives over P devices):
- ``collective-permute`` sends its whole operand once per execution.
- ``all-to-all`` with a P-piece tuple operand keeps one piece local and
  sends P-1 — wire bytes = (P-1) x piece bytes.
- scalar ``all-reduce``: the SPARSE models carry a flat +4 bytes for
  their phase-1 pmax scalar (it exists only on that path); the DENSE
  models carry no flat term — their per-level termination psum is
  outside every model's stated scope (exchange traffic) and is reported
  separately here.
- wire dtypes: the unpacked dense ring ships PRED chunks (one BYTE per
  vertex per hop — n result bytes pins the dtype), the unpacked
  allreduce an S32 buffer (four bytes per vertex); ``wire_pack`` ships
  U32 words, 32 vertices/word, on both (``check_packed_exchange``
  asserts the exact /8 and /32 ratios plus an unchanged collective
  instruction count).
"""

from __future__ import annotations

import numpy as np

# The HLO walking core moved to tpu_bfs/analysis/hlo.py (ISSUE 8): the
# shape/byte parsing and collective inventory are shared with the
# static-analysis passes now; this module keeps the wire-byte AUDITS and
# re-exports the core names its tests and clients import from here.
from tpu_bfs.analysis.hlo import (  # noqa: F401 — re-exported API
    Collective,
    hlo_collectives,
    shape_bytes as _shape_bytes,
)


def _lower_1d_loop(eng) -> str:
    """Compiled HLO text of a 1D DistBfsEngine's level loop."""
    import jax.numpy as jnp

    f0, vis0, d0 = eng._init_state(0)
    return (
        eng._loop.lower(
            eng.src, eng.dst, eng.rp, eng._aux, f0, vis0, d0,
            jnp.int32(0), jnp.int32(64),
        )
        .compile()
        .as_text()
    )


def check_1d_sparse(graph, p: int = 8, wire_pack: bool = False) -> dict:
    """1D DistBfsEngine, queue-style sparse exchange: the modeled per-level
    branch bytes (sparse_wire_bytes_per_level) vs the compiled program's
    all-to-all piece sizes and ring-step permutes. ``wire_pack`` audits
    the bit-packed dense fallback (u32 word permutes) against the packed
    model and the recalibrated default cap ladder."""
    from tpu_bfs.parallel.collectives import sparse_wire_bytes_per_level
    from tpu_bfs.parallel.dist_bfs import DistBfsEngine, make_mesh

    eng = DistBfsEngine(
        graph, make_mesh(p), exchange="sparse", wire_pack=wire_pack
    )
    n = eng.part.vloc
    colls = hlo_collectives(_lower_1d_loop(eng))

    # Sparse branches: each cap's [P, cap] s32 bucket buffer all-to-all
    # keeps the self piece local -> (P-1) * 4c on the wire.
    a2a_wire = sorted(
        {(c.pieces - 1) * (c.result_bytes // c.pieces)
         for c in colls if c.op == "all-to-all"}
    )
    # Dense fallback: unrolled ring reduce-scatter, P-1 permutes of one
    # [n] bool chunk each (one [ceil(n/32)] u32 chunk under wire_pack).
    ring = [c for c in colls if c.op == "collective-permute"]
    ring_wire = sum(c.result_bytes for c in ring)
    scalars = [c for c in colls if c.op == "all-reduce"]

    modeled = sparse_wire_bytes_per_level(
        p, n, eng.sparse_caps, wire_pack=wire_pack
    )
    derived = [w + 4.0 for w in a2a_wire] + [ring_wire + 4.0]
    return {
        "config": (
            f"1D sparse exchange, P={p}, vloc={n}, caps={eng.sparse_caps}, "
            f"wire_pack={wire_pack}"
        ),
        "modeled_per_level": modeled,
        "hlo_per_level": derived,
        "ring_steps": len(ring),
        "scalar_allreduces": len(scalars),
        "agree": (
            [float(x) for x in modeled] == [float(x) for x in derived]
            and len(ring) == p - 1
        ),
    }


def check_2d(graph, rows: int = 2, cols: int = 4, exchange: str = "ring",
             backend: str = "scan", wire_pack: bool = False) -> dict:
    """2D Dist2DBfsEngine: the modeled per-level bytes (dense_2d_wire_bytes
    — the BASELINE scale-26 config's wire model) vs the compiled loop's
    column all-gather and row reduce-scatter.

    Ring conventions as in the module docstring; ``all-gather`` result
    holds all R pieces, so wire/chip = result - own piece = result*(R-1)/R.
    The 'allreduce' row exchange lowers to one [C*w] s32 all-reduce whose
    bandwidth-optimal wire cost is 2*(C-1)/C x result bytes. Under
    ``wire_pack`` the column gather moves u32[R*ceil(w/32)] words, the
    ring permutes u32[ceil(w/32)] chunks, and the allreduce row exchange
    becomes one all-to-all of per-destination word chunks (keep-own
    convention, as in the 1D packed audit)."""
    import jax.numpy as jnp

    from tpu_bfs.parallel.collectives import dense_2d_wire_bytes, packed_words
    from tpu_bfs.parallel.dist_bfs2d import Dist2DBfsEngine, make_mesh_2d

    eng = Dist2DBfsEngine(
        graph, make_mesh_2d(rows, cols), exchange=exchange, backend=backend,
        wire_pack=wire_pack,
    )
    w = eng.part.w
    nw = packed_words(w)
    f0, vis0, d0 = eng._init_state(0)
    hlo = (
        eng._loop.lower(
            eng.src_g, eng.dst_l, eng.rp, eng._aux, f0, vis0, d0,
            jnp.int32(0), jnp.int32(64),
        )
        .compile()
        .as_text()
    )
    colls = hlo_collectives(hlo)

    # Column exchange: one pred[R*w] (u32[R*nw] packed) all-gather over 'r'.
    ag_result = rows * 4 * nw if wire_pack else rows * w
    col_ags = [
        c for c in colls if c.op == "all-gather" and c.result_bytes == ag_result
    ]
    ag_wire = (rows - 1) * (ag_result // rows) if rows > 1 else 0

    if exchange == "ring":
        # Row exchange: unrolled ring, C-1 permutes of one pred[w]
        # (u32[nw] packed) chunk.
        chunk = 4 * nw if wire_pack else w
        ring = [
            c for c in colls
            if c.op == "collective-permute" and c.result_bytes == chunk
        ]
        row_wire = sum(c.result_bytes for c in ring)
        row_ok = len(ring) == cols - 1
    elif wire_pack:
        # Packed row exchange: one u32[C, nw] all-to-all, keep-own piece.
        a2as = [
            c for c in colls
            if c.op == "all-to-all" and c.result_bytes == 4 * cols * nw
        ]
        row_wire = sum(
            (c.pieces - 1) * (c.result_bytes // c.pieces) for c in a2as
        )
        row_ok = len(a2as) == 1
    else:
        # Row exchange: one s32[C*w] all-reduce (psum) over 'c'.
        big_ars = [
            c for c in colls
            if c.op == "all-reduce" and c.result_bytes == 4 * cols * w
        ]
        row_wire = sum(
            2 * (cols - 1) * c.result_bytes // cols for c in big_ars
        )
        row_ok = len(big_ars) == 1
    scalars = [
        c for c in colls if c.op == "all-reduce" and c.result_bytes == 4
    ]

    modeled = dense_2d_wire_bytes(rows, cols, w, exchange, wire_pack=wire_pack)
    derived = float(ag_wire + row_wire)
    return {
        "config": (
            f"2D {exchange}/{backend}, mesh {rows}x{cols}, w={w}, "
            f"wire_pack={wire_pack}"
        ),
        "modeled_per_level": modeled,
        "hlo_per_level": derived,
        "column_allgathers": len(col_ags),
        "scalar_allreduces": len(scalars),
        # A 1-row mesh column-exchanges nothing: no all-gather to find.
        "agree": (
            modeled == derived
            and len(col_ags) == (1 if rows > 1 else 0)
            and row_ok
        ),
    }


def check_planned_sparse(graph, p: int = 8, wire_pack: bool = False) -> dict:
    """ISSUE 7 tentpole proof, from the compiled HLO: the exchange
    planner's delta branches ship exactly ``delta_words(cap, b)`` =
    1 + ceil(cap*b/32) uint32 words per destination (one header word +
    the bit-packed deltas), the sieve path adds EXACTLY ONE packed vis
    transfer (a u32[ceil(n/32)] all-gather — nothing else in the 1D loop
    all-gathers), and the whole branch space prices to the model: every
    entry of planned_sparse_wire_bytes_per_level is re-derived from the
    collectives' own operand shapes.

    Collective inventory audited (delta_bits=(8,16), sieve+predict on):
    each (cap rung x {delta8, delta16, plain}) all-to-all appears TWICE —
    once unsieved, once sieved (consumed pairwise, so a program missing a
    sieved rung fails); the dense ring appears THREE times (unsieved
    fallback, sieved fallback, predicted-dense) at P-1 permutes each; the
    measured pmax is ONE s32[2] all-reduce per measure (two instances:
    pre- and post-sieve) — the pair rides one scalar collective, which is
    why measured levels model +8, sieved +16, predicted +0."""
    from tpu_bfs.parallel.collectives import (
        DELTA_BITS_DEFAULT,
        delta_words,
        packed_words,
        planned_sparse_wire_bytes_per_level,
    )
    from tpu_bfs.parallel.dist_bfs import DistBfsEngine, make_mesh

    delta_bits = DELTA_BITS_DEFAULT
    eng = DistBfsEngine(
        graph, make_mesh(p), exchange="sparse", wire_pack=wire_pack,
        delta_bits=delta_bits, sieve=True, predict=True,
    )
    n = eng.part.vloc
    nw = packed_words(n)
    caps = eng.sparse_caps
    colls = hlo_collectives(_lower_1d_loop(eng))
    pool = list(colls)

    def _take(pred) -> bool:
        for idx, c in enumerate(pool):
            if pred(c):
                del pool[idx]
                return True
        return False

    # Per-rung piece bytes, in branch order (delta widths then plain).
    piece_bytes = []
    for c in sorted(caps):
        piece_bytes += [4 * delta_words(c, b) for b in delta_bits]
        piece_bytes.append(4 * c)
    found_pairs = []
    for piece in piece_bytes:
        # Consume the unsieved AND sieved instance of this rung/encoding.
        got = sum(
            _take(
                lambda a: a.op == "all-to-all"
                and a.pieces == p
                and a.result_bytes == piece * p
            )
            for _ in range(2)
        )
        found_pairs.append(got == 2)
    leftover_a2a = [c for c in pool if c.op == "all-to-all"]
    # The sieve's vis transfer: exactly ONE all-gather in the whole loop.
    ags = [c for c in pool if c.op == "all-gather"]
    sieve_ok = len(ags) == 1 and ags[0].result_bytes == p * 4 * nw
    # Dense ring: three instances (unsieved, sieved, predicted) of P-1
    # permutes each, pred[n] chunks (u32[nw] under wire_pack).
    chunk = 4 * nw if wire_pack else n
    perms = [c for c in pool if c.op == "collective-permute"]
    ring_ok = (
        len(perms) == 3 * (p - 1)
        and all(c.result_bytes == chunk for c in perms)
    )
    # Scalars: two s32[2] pmax pairs (pre/post-sieve measure), plus the
    # 4-byte termination psum and visited-total seed.
    pairs = [c for c in pool if c.op == "all-reduce" and c.result_bytes == 8]
    singles = [c for c in pool if c.op == "all-reduce" and c.result_bytes == 4]

    sparse_wire = [(p - 1) * piece for piece in piece_bytes]
    ring_wire = float((p - 1) * chunk)
    ag_wire = float((p - 1) * 4 * nw)
    derived = (
        [w + 8.0 for w in sparse_wire] + [ring_wire + 8.0]
        + [w + ag_wire + 16.0 for w in sparse_wire]
        + [ring_wire + ag_wire + 16.0] + [ring_wire]
    )
    modeled = planned_sparse_wire_bytes_per_level(
        p, n, caps, delta_bits, wire_pack=wire_pack
    )
    return {
        "config": (
            f"planned sparse exchange, P={p}, vloc={n}, caps={caps}, "
            f"delta_bits={delta_bits}, wire_pack={wire_pack}"
        ),
        "modeled_per_level": modeled,
        "hlo_per_level": derived,
        "rung_pairs_found": found_pairs,
        "sieve_allgathers": len(ags),
        "ring_permutes": len(perms),
        "pair_pmaxes": len(pairs),
        "scalar_allreduces": len(singles),
        "agree": (
            all(found_pairs)
            and not leftover_a2a
            and sieve_ok
            and ring_ok
            and len(pairs) == 2
            and [float(x) for x in modeled] == [float(x) for x in derived]
        ),
    }


def check_rows_delta(graph, p: int = 8, lanes: int = 64) -> dict:
    """Delta-encoded sparse row gather (ISSUE 7, distributed wide engine —
    the hybrid shares the code path): per rung, the id stream compresses
    to ONE u32[delta_words(cap, b)] all-gather per width (plus the shared
    [cap, w] lane-word gather, which the encoding cannot touch), and the
    whole branch space prices to sparse_rows_wire_bytes_per_level."""
    import jax.numpy as jnp

    from tpu_bfs.parallel.collectives import (
        DELTA_BITS_DEFAULT,
        delta_words,
        sparse_rows_wire_bytes_per_level,
    )
    from tpu_bfs.parallel.dist_bfs import make_mesh
    from tpu_bfs.parallel.dist_msbfs_wide import DistWideMsBfsEngine

    delta_bits = DELTA_BITS_DEFAULT
    eng = DistWideMsBfsEngine(
        graph, make_mesh(p), lanes=lanes, exchange="sparse",
        delta_bits=delta_bits,
    )
    w = eng.w
    rows_loc = eng._gather_rows_loc
    caps = eng.sparse_caps
    fw0 = eng._seed_dev(np.asarray([0]))
    hlo = (
        eng._dist_core.lower(eng.arrs, fw0, jnp.int32(32)).compile().as_text()
    )
    ags = [c for c in hlo_collectives(hlo) if c.op == "all-gather"]
    pool = list(ags)

    def _take(pred) -> bool:
        for idx, a in enumerate(pool):
            if pred(a):
                del pool[idx]
                return True
        return False

    derived = []
    found = []
    for c in sorted(caps):
        vals_b = p * c * 4 * w
        got_vals = _take(lambda a: a.result_bytes == vals_b and a.pieces == 1)
        for b in delta_bits:
            ids_b = p * 4 * delta_words(c, b)
            got = _take(lambda a: a.result_bytes == ids_b and a.pieces == 1)
            found.append(got)
            derived.append(
                None if not (got and got_vals)
                else (ids_b + vals_b) * (p - 1) / p + 8.0
            )
        ids_plain = p * c * 4
        got = _take(lambda a: a.result_bytes == ids_plain and a.pieces == 1)
        found.append(got and got_vals)
        derived.append(
            None if not (got and got_vals)
            else (ids_plain + vals_b) * (p - 1) / p + 8.0
        )
    dense_b = p * rows_loc * 4 * w
    dense_got = _take(lambda a: a.result_bytes == dense_b)
    found.append(dense_got)
    derived.append(dense_b * (p - 1) / p + 8.0 if dense_got else None)

    modeled = sparse_rows_wire_bytes_per_level(
        p, rows_loc, w, caps, delta_bits
    )
    return {
        "config": (
            f"dist-wide delta rows, P={p}, rows_loc={rows_loc}, w={w}, "
            f"caps={caps}, delta_bits={delta_bits}"
        ),
        "modeled_per_level": modeled,
        "hlo_per_level": derived,
        "all_gathers": len(ags),
        "agree": (
            all(found)
            and [float(x) for x in modeled] == [float(x) for x in derived]
        ),
    }


def check_2d_sparse(graph, rows: int = 2, cols: int = 4) -> dict:
    """2D queue-style ROW exchange (ISSUE 7): the 2D engine's sparse mode
    runs sparse_exchange_or over 'c' — the modeled per-branch bytes
    (column all-gather + sparse rung / ring fallback) vs the compiled
    loop's own collective shapes."""
    import jax.numpy as jnp

    from tpu_bfs.parallel.dist_bfs2d import Dist2DBfsEngine, make_mesh_2d

    eng = Dist2DBfsEngine(
        graph, make_mesh_2d(rows, cols), exchange="sparse"
    )
    w = eng.part.w
    caps = eng.sparse_caps
    f0, vis0, d0 = eng._init_state(0)
    hlo = (
        eng._loop.lower(
            eng.src_g, eng.dst_l, eng.rp, eng._aux, f0, vis0, d0,
            jnp.int32(0), jnp.int32(64),
        )
        .compile()
        .as_text()
    )
    colls = hlo_collectives(hlo)
    col_ags = [
        c for c in colls
        if c.op == "all-gather" and c.result_bytes == rows * w
    ]
    ag_wire = (rows - 1) * w if rows > 1 else 0
    a2a_wire = sorted(
        {(c.pieces - 1) * (c.result_bytes // c.pieces)
         for c in colls if c.op == "all-to-all"}
    )
    ring = [
        c for c in colls
        if c.op == "collective-permute" and c.result_bytes == w
    ]
    derived = [ag_wire + x + 4.0 for x in a2a_wire] + [
        ag_wire + sum(c.result_bytes for c in ring) + 4.0
    ]
    modeled = eng.wire_bytes_per_level()
    return {
        "config": (
            f"2D sparse row exchange, mesh {rows}x{cols}, w={w}, caps={caps}"
        ),
        "modeled_per_level": modeled,
        "hlo_per_level": derived,
        "column_allgathers": len(col_ags),
        "ring_steps": len(ring),
        "agree": (
            [float(x) for x in modeled] == [float(x) for x in derived]
            and len(col_ags) == (1 if rows > 1 else 0)
            and len(ring) == cols - 1
        ),
    }


def check_rows_sparse(graph, p: int = 8, lanes: int = 64) -> dict:
    """Distributed wide engine, queue-style sparse row gather
    (collectives.sparse_rows_gather, shared with the distributed hybrid):
    the modeled per-branch bytes (sparse_rows_wire_bytes_per_level) vs the
    compiled cap-ladder's all-gather sizes.

    Each sparse rung c gathers (ids s32[c], vals u32[c, w]) from every
    chip; XLA's all-gather combiner may emit them as two array ops or one
    tuple op, so both forms are accepted. Wire/chip = (P-1)/P x gathered
    result bytes, + the 4-byte pmax scalar every branch pays. The dense
    fallback gathers the whole [v_loc, w] slab."""
    import jax.numpy as jnp

    from tpu_bfs.parallel.collectives import sparse_rows_wire_bytes_per_level
    from tpu_bfs.parallel.dist_bfs import make_mesh
    from tpu_bfs.parallel.dist_msbfs_wide import DistWideMsBfsEngine

    eng = DistWideMsBfsEngine(
        graph, make_mesh(p), lanes=lanes, exchange="sparse"
    )
    w = eng.w
    rows_loc = eng._gather_rows_loc
    caps = eng.sparse_caps
    fw0 = eng._seed_dev(np.asarray([0]))
    hlo = (
        eng._dist_core.lower(eng.arrs, fw0, jnp.int32(32)).compile().as_text()
    )
    ags = [c for c in hlo_collectives(hlo) if c.op == "all-gather"]
    pool = list(ags)  # ops are CONSUMED as rungs match (see below)

    def _take(pred) -> bool:
        for i, a in enumerate(pool):
            if pred(a):
                del pool[i]
                return True
        return False

    def rung_result_bytes(ids_b: int, vals_b: int):
        """Gathered result bytes of one rung, from the HLO's own ops —
        separate ids/vals all-gathers or one combined tuple op. Matched
        ops are consumed so size collisions between rungs (cap_j*4 ==
        cap_i*4w, or ids_b == vals_b at w=1) can't let one op vouch for
        two probes — a program genuinely missing a rung must fail."""
        if _take(lambda a: a.result_bytes == ids_b + vals_b and a.pieces == 2):
            return ids_b + vals_b
        if _take(lambda a: a.result_bytes == ids_b and a.pieces == 1):
            if _take(lambda a: a.result_bytes == vals_b and a.pieces == 1):
                return ids_b + vals_b
        return None

    derived = []
    found = []
    for c in sorted(caps):
        got = rung_result_bytes(p * c * 4, p * c * 4 * w)
        found.append(got is not None)
        derived.append(
            None if got is None else got * (p - 1) / p + 4.0
        )
    dense_b = p * rows_loc * 4 * w
    dense_got = _take(lambda a: a.result_bytes == dense_b)
    found.append(dense_got)
    derived.append(dense_b * (p - 1) / p + 4.0 if dense_got else None)

    modeled = sparse_rows_wire_bytes_per_level(p, rows_loc, w, caps)
    return {
        "config": (
            f"dist-wide sparse rows, P={p}, rows_loc={rows_loc}, w={w}, "
            f"caps={caps}"
        ),
        "modeled_per_level": modeled,
        "hlo_per_level": derived,
        "all_gathers": len(ags),
        "agree": (
            all(found)
            and [float(x) for x in modeled]
            == [float(x) for x in derived]
        ),
    }


def check_minplus_exchange(graph, p: int = 8, lanes: int = 32) -> dict:
    """ISSUE 20 tentpole proof, from the compiled HLO: the (min, +) value
    exchange (collectives.sparse_rows_exchange_min, the delta-stepping
    engines' bucket-close collective) prices exactly like its OR row-gather
    twin with the lane payload reinterpreted — per rung ONE [cap, lanes]
    s32 value all-gather shared across the id encodings, one id all-gather
    per encoding (delta_words(cap, b) u32 words delta-encoded, cap int32s
    plain), ONE s32[2] pmax pair per measured round — and the history
    predictor's armed branch adds EXACTLY one extra dense table all-gather
    (the measurement-free round) and nothing else.

    Three compiles are audited against minplus_rows_wire_bytes_per_level:

    - the planner variant (delta_bits + predict): every branch's modeled
      bytes re-derived from the collectives' own operand shapes, matched
      ops CONSUMED so no op vouches twice, zero leftover all-gathers;
    - the measured variant (predict off) vs the OR counterpart
      (DistWideMsBfsEngine, SAME cap ladder / delta widths / lane count):
      all-gather instruction counts must be EQUAL — generalizing the
      monoid adds no collective;
    - planner vs measured: all-gather count delta must be EXACTLY one
      (the predicted-dense branch's table rebuild).
    """
    import jax.numpy as jnp

    from tpu_bfs.parallel.collectives import (
        DELTA_BITS_DEFAULT,
        delta_words,
        minplus_rows_wire_bytes_per_level,
    )
    from tpu_bfs.parallel.dist_bfs import make_mesh
    from tpu_bfs.parallel.dist_msbfs_wide import DistWideMsBfsEngine
    from tpu_bfs.parallel.dist_sssp import DistSsspEngine

    delta_bits = DELTA_BITS_DEFAULT
    mesh = make_mesh(p)

    def sssp_ags(predict: bool):
        eng = DistSsspEngine(
            graph, mesh, lanes=lanes, exchange="sparse",
            delta_bits=delta_bits, predict=predict,
        )
        progs = {nm: (fn, args) for nm, fn, args in eng.analysis_programs()}
        fn, args = progs["dist_sssp_core"]
        colls = hlo_collectives(fn.lower(*args).compile().as_text())
        return eng, colls

    eng, colls = sssp_ags(predict=True)
    n = eng.sell.v_loc
    caps = eng.sparse_caps
    pool = [c for c in colls if c.op == "all-gather"]
    n_ags_planner = len(pool)

    def _take(pred) -> bool:
        for idx, a in enumerate(pool):
            if pred(a):
                del pool[idx]
                return True
        return False

    derived = []
    found = []
    for c in sorted(caps):
        # One shared [cap, lanes] s32 value gather per rung, then one id
        # gather per encoding (delta widths in ladder order, then plain).
        vals_b = p * c * 4 * lanes
        got_vals = _take(lambda a: a.result_bytes == vals_b and a.pieces == 1)
        for b in delta_bits:
            ids_b = p * 4 * delta_words(c, b)
            got = _take(lambda a: a.result_bytes == ids_b and a.pieces == 1)
            found.append(got and got_vals)
            derived.append(
                None if not (got and got_vals)
                else (ids_b + vals_b) * (p - 1) / p + 8.0
            )
        ids_plain = p * c * 4
        got = _take(lambda a: a.result_bytes == ids_plain and a.pieces == 1)
        found.append(got and got_vals)
        derived.append(
            None if not (got and got_vals)
            else (ids_plain + vals_b) * (p - 1) / p + 8.0
        )
    # Dense table rebuild: the measured ladder's overflow leaf AND the
    # predictor's measurement-free branch each all-gather every chip's
    # [v_loc, lanes] owned-row slab — two instances, same shape; only the
    # measured one pays the s32[2] pmax.
    dense_b = p * n * 4 * lanes
    for flat in (8.0, 0.0):
        got = _take(lambda a: a.result_bytes == dense_b and a.pieces == 1)
        found.append(got)
        derived.append(dense_b * (p - 1) / p + flat if got else None)
    # The pmax pair (changed-row count + max id gap) rides ONE s32[2]
    # all-reduce; the per-round light-sweep convergence psum is the 4-byte
    # scalar, outside the exchange model by the dense_or convention.
    pairs = [c for c in colls if c.op == "all-reduce" and c.result_bytes == 8]

    modeled = minplus_rows_wire_bytes_per_level(
        p, n, lanes, caps, delta_bits, predict=True
    )

    # Monoid-generalization certificate: same ladder, same encodings, same
    # lane count -> the min exchange compiles to exactly as many
    # all-gathers as the OR row gather (predict off), and arming the
    # predictor adds exactly the one dense rebuild.
    _, colls_meas = sssp_ags(predict=False)
    n_ags_measured = len([c for c in colls_meas if c.op == "all-gather"])
    eng_or = DistWideMsBfsEngine(
        graph, mesh, lanes=lanes, exchange="sparse", delta_bits=delta_bits,
        sparse_caps=caps,
    )
    fw0 = eng_or._seed_dev(np.asarray([0]))
    hlo_or = (
        eng_or._dist_core.lower(eng_or.arrs, fw0, jnp.int32(32))
        .compile().as_text()
    )
    n_ags_or = len([c for c in hlo_collectives(hlo_or) if c.op == "all-gather"])

    return {
        "config": (
            f"min-plus rows exchange, P={p}, v_loc={n}, lanes={lanes}, "
            f"caps={caps}, delta_bits={delta_bits}, predict=True"
        ),
        "modeled_per_level": modeled,
        "hlo_per_level": derived,
        "all_gathers": {
            "minplus_planner": n_ags_planner,
            "minplus_measured": n_ags_measured,
            "or_rows": n_ags_or,
        },
        "pair_pmaxes": len(pairs),
        "agree": (
            all(found)
            and not [c for c in pool if c.op == "all-gather"]
            and len(pairs) == 1
            and n_ags_measured == n_ags_or
            and n_ags_planner == n_ags_measured + 1
            and [float(x) for x in modeled] == [float(x) for x in derived]
        ),
    }


def check_packed_exchange(graph, p: int = 8) -> dict:
    """ISSUE 5 tentpole proof, from the compiled HLO: the bit-packed wire
    format moves exactly 1/8 the collective bytes of the pred ring and
    exactly 1/32 the collective operand bytes of the s32 allreduce, with
    an IDENTICAL collective instruction count — packing is pure compute,
    it never adds a collective.

    Compiles the 1D level loop four ways (ring/allreduce x plain/packed)
    and derives everything from the instructions' own shapes:

    - ring: P-1 collective-permutes both ways; plain chunks are pred[n]
      (n result bytes — ONE byte per vertex, pinning the dtype the model
      documents), packed chunks u32[ceil(n/32)]. vloc is 1024-aligned by
      partition_1d, so the /8 ratio is exact, never ceil-rounded.
    - allreduce: ONE collective both ways; plain is an s32[P*n] all-reduce
      (4 bytes per vertex), packed is one u32 all-to-all whose operand is
      P*n/8 bytes — exactly 1/32. (The packed form also sheds the psum's
      all-gather half, so its modeled WIRE bytes, keep-own convention,
      equal the packed ring's — dense_or_wire_bytes says so.)
    """
    from tpu_bfs.parallel.collectives import dense_or_wire_bytes, packed_words
    from tpu_bfs.parallel.dist_bfs import DistBfsEngine, make_mesh

    mesh = make_mesh(p)
    colls, n = {}, None
    for impl in ("ring", "allreduce"):
        for packed in (False, True):
            eng = DistBfsEngine(graph, mesh, exchange=impl, wire_pack=packed)
            n = eng.part.vloc
            colls[impl, packed] = hlo_collectives(_lower_1d_loop(eng))
    nw = packed_words(n)

    def wire(c: Collective) -> int:
        # Permutes send their operand; an all-to-all keeps its own piece.
        if c.op == "all-to-all":
            return (c.pieces - 1) * (c.result_bytes // c.pieces)
        return c.result_bytes

    ring_plain = [
        c for c in colls["ring", False] if c.op == "collective-permute"
    ]
    ring_packed = [
        c for c in colls["ring", True] if c.op == "collective-permute"
    ]
    # The big exchange all-reduce; the 4-byte scalars are the termination
    # psums, present identically in every variant.
    ar_plain = [
        c for c in colls["allreduce", False]
        if c.op == "all-reduce" and c.result_bytes > 4
    ]
    a2a_packed = [c for c in colls["allreduce", True] if c.op == "all-to-all"]

    ring_bytes = sum(wire(c) for c in ring_plain)
    ring_packed_bytes = sum(wire(c) for c in ring_packed)
    ar_operand = sum(c.result_bytes for c in ar_plain)
    a2a_operand = sum(c.result_bytes for c in a2a_packed)
    counts = {
        (impl, packed): len(cs) for (impl, packed), cs in colls.items()
    }
    modeled = {
        impl: dense_or_wire_bytes(p, n, impl, wire_pack=True)
        for impl in ("ring", "allreduce")
    }
    derived = {
        "ring": float(ring_packed_bytes),
        "allreduce": float(sum(wire(c) for c in a2a_packed)),
    }
    return {
        "config": f"packed vs plain 1D exchange, P={p}, vloc={n}",
        "vloc": n,
        "ring_permute_result_bytes": sorted(
            {c.result_bytes for c in ring_plain}
        )[0] if ring_plain else None,
        "allreduce_operand_bytes": ar_operand,
        "ring_reduction": ring_bytes / ring_packed_bytes
        if ring_packed_bytes else None,
        "allreduce_operand_reduction": ar_operand / a2a_operand
        if a2a_operand else None,
        "collective_counts": {f"{i}/{p_}": c for (i, p_), c in counts.items()},
        "modeled_packed_per_level": modeled,
        "hlo_packed_per_level": derived,
        "agree": (
            len(ring_plain) == len(ring_packed) == p - 1
            and len(ar_plain) == 1
            and len(a2a_packed) == 1
            and counts["ring", True] == counts["ring", False]
            and counts["allreduce", True] == counts["allreduce", False]
            and ring_packed_bytes * 8 == ring_bytes
            and a2a_operand * 32 == ar_operand
            and derived == {k: float(v) for k, v in modeled.items()}
            and ring_packed_bytes == (p - 1) * 4 * nw
        ),
    }


def check_wire_checksum(p: int = 8, words: int = 64) -> dict:
    """ISSUE 15 wire-checksum byte proof, from the compiled HLO: the
    per-hop chunk checksum (integrity/wire.checksummed_ring_or) costs
    EXACTLY one uint32 word — 4 bytes — per chunk per hop, with an
    identical collective instruction count (the fold is pure compute;
    framing never adds a collective). Compiles the checksummed packed
    ring reduce-scatter-OR both ways over the real ``p``-device mesh and
    derives everything from the permutes' own result shapes:

    - both variants emit exactly ``p - 1`` collective-permutes;
    - plain chunks are ``u32[words]`` (4 * words bytes), framed chunks
      ``u32[words + 1]`` — the delta is 4 bytes per hop, total
      ``4 * (p - 1)`` per shard per exchange;
    - the two programs' results are bit-identical on clean wires (the
      OR semantics are untouched; pinned separately in
      tests/test_integrity.py)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from tpu_bfs.integrity.wire import checksummed_ring_or
    from tpu_bfs.parallel.compat import shard_map

    devs = jax.devices()[:p]
    mesh = Mesh(np.array(devs), ("x",))
    chunks = jnp.zeros((p, p, words), jnp.uint32)

    def lower(wire_check: bool) -> str:
        def body(c):
            out, bad = checksummed_ring_or(
                c[0], "x", wire_check=wire_check
            )
            return out[None], bad[None]

        fn = shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=(P("x"), P("x")),
        )
        return jax.jit(fn).lower(chunks).compile().as_text()

    colls = {
        checked: [
            c for c in hlo_collectives(lower(checked))
            if c.op == "collective-permute"
        ]
        for checked in (False, True)
    }
    plain_bytes = sum(c.result_bytes for c in colls[False])
    checked_bytes = sum(c.result_bytes for c in colls[True])
    counts = {
        checked: len(hlo_collectives(lower(checked)))
        for checked in (False, True)
    }
    return {
        "config": f"checksummed packed ring, P={p}, {words} words/chunk",
        "permutes": {c: len(v) for c, v in colls.items()},
        "plain_permute_bytes": plain_bytes,
        "checked_permute_bytes": checked_bytes,
        "checksum_overhead_bytes": checked_bytes - plain_bytes,
        "collective_counts": counts,
        "agree": (
            len(colls[False]) == len(colls[True]) == p - 1
            and counts[True] == counts[False]
            and checked_bytes - plain_bytes == 4 * (p - 1)
            and all(c.result_bytes == 4 * words for c in colls[False])
            and all(c.result_bytes == 4 * (words + 1) for c in colls[True])
        ),
    }


def check_gated_hybrid(graph, p: int = 8, exchange: str = "dense") -> dict:
    """Pull-gated distributed hybrid (ISSUE 1): the gate must move ZERO
    extra collective bytes — its settled mask is chip-resident, and its
    per-level skipped-block counters come back per-chip (a sharded
    [P, L] output summed on host, deliberately not a psum). Proof: compile
    the gated and ungated cores for the same graph/mesh/exchange and
    compare the full multiset of collective instructions (op, result
    bytes, tuple arity) — equality means the gated program's exchange is
    instruction-for-instruction the ungated one's. Works for every
    exchange the engine grows the flag on ('dense', 'sparse', 'sliced')."""
    import jax.numpy as jnp

    from tpu_bfs.parallel.dist_bfs import make_mesh
    from tpu_bfs.parallel.dist_msbfs_hybrid import DistHybridMsBfsEngine

    mesh = make_mesh(p)
    colls = {}
    for gate in (False, True):
        eng = DistHybridMsBfsEngine(
            graph, mesh, exchange=exchange, pull_gate=gate
        )
        args = (eng.arrs, eng._seed_dev(np.asarray([0])), jnp.int32(32))
        if gate:
            args = args + (eng._lane_mask_dev,)
        hlo = eng._dist_core.lower(*args).compile().as_text()
        colls[gate] = sorted(
            (c.op, c.result_bytes, c.pieces) for c in hlo_collectives(hlo)
        )
    return {
        "config": f"gated-vs-ungated dist hybrid, P={p}, exchange={exchange}",
        "ungated_collectives": colls[False],
        "gated_collectives": colls[True],
        "agree": colls[False] == colls[True] and len(colls[False]) > 0,
    }


def check_sliced_hybrid(graph, p: int = 8, lanes: int | None = None) -> dict:
    """Ring-sliced distributed hybrid: the modeled dense-slab bytes
    ((P-1) x [rows_loc, w] u32 per level) vs the compiled rotation's
    permute operand and the engine's own static ring-step count.
    ``lanes`` widens the rows (the model is width-generic; the w=256 arm
    calibrates it at the round-4 single-chip default width)."""
    import jax.numpy as jnp

    from tpu_bfs.parallel.dist_bfs import make_mesh
    from tpu_bfs.parallel.dist_msbfs_hybrid import DistHybridMsBfsEngine

    kw = {} if lanes is None else {"lanes": lanes}
    eng = DistHybridMsBfsEngine(graph, make_mesh(p), exchange="sliced", **kw)
    rows_loc = eng._gather_rows_loc
    fw0 = eng._seed_dev(np.asarray([0]))
    hlo = (
        eng._dist_core.lower(eng.arrs, fw0, jnp.int32(32)).compile().as_text()
    )
    perms = [
        c for c in hlo_collectives(hlo) if c.op == "collective-permute"
    ]
    slab = rows_loc * eng.w * 4
    # The rotation rides a lax.scan whose trip count is the per-step axis
    # of the step arrays minus the unrotated first step — static, read
    # from the engine's own tables rather than parsed out of the while
    # condition. The GLOBAL array is [P_devices, P_steps, ...] with the
    # device-sharding axis first; inside shard_map each chip scans axis 1.
    # (shape[0] would coincide today only because steps+1 == P.)
    steps = int(eng.arrs["perm"].shape[1]) - 1
    modeled = 0.0 if p == 1 else float((p - 1) * rows_loc * 4 * eng.w)
    derived = float(steps * slab)
    return {
        "config": (
            f"sliced hybrid, P={p}, rows_loc={rows_loc}, w={eng.w}"
        ),
        "modeled_per_level": modeled,
        "hlo_per_level": derived,
        "permute_result_bytes": sorted({c.result_bytes for c in perms}),
        "ring_steps": steps,
        "agree": (
            modeled == derived
            and steps == p - 1
            and all(c.result_bytes == slab for c in perms)
            and len(perms) > 0
        ),
    }
