"""Result validation.

The reference validates distances elementwise and exits on first mismatch
(checkOutput, bfs.cu:374-384) and never validates parents — it can't: its
parent is an atomic-race winner stored as an edge index (bfs.cu:146-147, 940).
Here:

- ``check_distances``: the same elementwise oracle compare, as a function
  returning mismatches instead of exit(1).
- ``check_parents``: property-based BFS-tree validation in the Graph500 style:
  parent edges must exist in the graph and satisfy dist[parent[v]] ==
  dist[v] - 1; exactly the reached set has parents.
- ``min_parent_from_dist``: the deterministic min-parent tree implied by a
  distance array — the device kernels' parent definition, computable on host
  for exact comparison.
"""

from __future__ import annotations

import numpy as np

from tpu_bfs.graph.csr import Graph, INF_DIST, NO_PARENT


class ValidationError(AssertionError):
    pass


def check_distances(dist: np.ndarray, expected: np.ndarray, *, max_report: int = 10) -> None:
    """Elementwise distance compare (reference: checkOutput, bfs.cu:374-384)."""
    dist = np.asarray(dist)
    expected = np.asarray(expected)
    if dist.shape != expected.shape:
        raise ValidationError(f"shape mismatch: {dist.shape} vs {expected.shape}")
    bad = np.flatnonzero(dist != expected)
    if len(bad):
        lines = [
            f"  v={v}: got {dist[v]}, expected {expected[v]}" for v in bad[:max_report]
        ]
        raise ValidationError(
            f"{len(bad)} distance mismatches:\n" + "\n".join(lines)
        )


def check_parents(
    g: Graph, source: int, dist: np.ndarray, parent: np.ndarray
) -> None:
    """Property-based parent (BFS tree) validation.

    Checks, vectorized over all vertices:
      1. parent[source] == source and dist[source] == 0.
      2. v reached (dist < INF) and v != source  =>  parent[v] is reached,
         dist[parent[v]] == dist[v] - 1, and edge (parent[v], v) exists.
      3. v unreached  =>  parent[v] == NO_PARENT.
    """
    dist = np.asarray(dist)
    parent = np.asarray(parent)
    v_count = g.num_vertices
    if dist.shape != (v_count,) or parent.shape != (v_count,):
        raise ValidationError("dist/parent shape mismatch")
    if dist[source] != 0 or parent[source] != source:
        raise ValidationError(
            f"source: dist={dist[source]}, parent={parent[source]}"
        )
    reached = dist != INF_DIST
    if not np.all(parent[~reached] == NO_PARENT):
        raise ValidationError("unreached vertex with a parent")
    vs = np.flatnonzero(reached)
    vs = vs[vs != source]
    ps = parent[vs]
    if np.any(ps < 0) or np.any(ps >= v_count):
        raise ValidationError("reached vertex with out-of-range parent")
    bad_level = dist[ps] != dist[vs] - 1
    if np.any(bad_level):
        v = vs[np.argmax(bad_level)]
        raise ValidationError(
            f"v={v}: dist[parent]={dist[parent[v]]} but dist[v]={dist[v]}"
        )
    # Edge existence: every (parent[v], v) must be in the CSR. Fully
    # vectorized: pack endpoints into int64 keys and binary-search the packed,
    # sorted edge set (works for sorted or unsorted adjacency).
    src_all, dst_all = g.coo
    n = np.int64(g.num_vertices)
    edge_keys = np.sort(src_all.astype(np.int64) * n + dst_all)
    query = ps.astype(np.int64) * n + vs
    pos = np.searchsorted(edge_keys, query)
    pos = np.minimum(pos, len(edge_keys) - 1)
    found = edge_keys[pos] == query if len(edge_keys) else np.zeros(len(vs), bool)
    if not np.all(found):
        v = vs[np.argmin(found)]
        raise ValidationError(f"edge (parent[v]={parent[v]}, v={v}) not in graph")


def check_edge_levels(g: Graph, dist: np.ndarray) -> None:
    """Graph500-style edge-level property: for every directed edge slot
    (u, v) with u reached, ``dist[v] <= dist[u] + 1`` (an unreached v is a
    violation too: INF exceeds any du+1). For undirected graphs the CSR
    holds both orientations, so this single directional sweep implies
    |dist[u] - dist[v]| <= 1 and reached-iff-reached across every edge."""
    dist = np.asarray(dist).astype(np.int64)
    src, dst = g.coo
    du = dist[src]
    dv = dist[dst]
    bad = (du != INF_DIST) & (dv > du + 1)
    if np.any(bad):
        i = int(np.argmax(bad))
        raise ValidationError(
            f"edge ({src[i]}, {dst[i]}): dist {du[i]} -> {dv[i]} skips a level"
        )


def certify_bfs(
    g: Graph, source: int, dist: np.ndarray, parent: np.ndarray
) -> None:
    """ORACLE-FREE certification that (dist, parent) is a correct BFS of
    ``g`` from ``source`` — the Graph500 validation design (its spec
    validates kernel output by properties precisely because a sequential
    reference run is infeasible at scale; the CUDA reference instead
    reruns itself on the CPU, bfs.cu:798-815, which caps the graphs it
    can ever validate).

    The certificate is sound: :func:`check_parents` gives, for every
    reached v, a parent chain of strictly decreasing labels ending at the
    source — so dist[v] is the length of a REAL path, hence
    dist[v] >= d_true(v); :func:`check_edge_levels` gives
    dist[v] <= dist[u] + 1 across every edge, so by induction along any
    true shortest path dist[v] <= d_true(v). Together with the reached
    set being closed under edges (an unreached neighbor of a reached
    vertex fails the level check), equality holds everywhere: the labels
    ARE the BFS distances and the tree is a valid BFS tree.
    Cost: two vectorized O(E) host passes — independent of diameter,
    feasible at scales where a CPU golden run is not."""
    check_parents(g, source, dist, parent)
    check_edge_levels(g, dist)


def min_parent_from_dist(g: Graph, source: int, dist: np.ndarray) -> np.ndarray:
    """Deterministic min-parent tree implied by a distance array.

    parent[v] = min{ u : (u, v) in E, dist[u] == dist[v] - 1 } for reached
    v != source; source maps to itself; unreached to NO_PARENT. This is the
    exact tree the device kernels produce (scatter-min over predecessors),
    replacing the reference's nondeterministic atomic-race parent.
    """
    dist = np.asarray(dist).astype(np.int64)
    src, dst = g.coo
    # Predecessor candidates: edge (u, v) with dist[u] + 1 == dist[v].
    du = dist[src]
    dv = dist[dst]
    ok = (du != INF_DIST) & (du + 1 == dv)
    parent = np.full(g.num_vertices, np.iinfo(np.int64).max, dtype=np.int64)
    np.minimum.at(parent, dst[ok], src[ok])
    out = np.where(parent == np.iinfo(np.int64).max, NO_PARENT, parent).astype(np.int32)
    out[dist == INF_DIST] = NO_PARENT
    out[source] = source
    return out
