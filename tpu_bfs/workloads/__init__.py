"""tpu_bfs/workloads — the query-kind subsystem over the MS-BFS substrate
(ISSUE 14).

The packed lane machinery (dispatch/fetch protocol, on-device lane
summaries, width ladder, fuzz oracle) is a general multi-source traversal
substrate; this package widens what it answers from one query type to
five, each served through the same coalescing/ladder/OOM/breaker path
behind a ``"kind"`` axis:

========  ==========================================================
kind      semantics (and substrate)
========  ==========================================================
bfs       single-source BFS distances — the original query (the base
          engines themselves; no adapter).
sssp      single-source shortest paths over the WEIGHTED graph:
          bucketed delta-stepping on int32 tentative distances over
          the same ELL tiles (workloads/sssp.py), light edges relaxed
          to a fixed point per bucket, heavy edges once at bucket
          close — Buluç & Madduri's framing of SSSP as the same
          frontier-expansion kernel as BFS (arXiv:1104.4518).
cc        connected components: repeated MS-BFS sweeps with lane
          recycling (finished lanes re-seeded from the unvisited
          set), per-row labels folded ON DEVICE via a min-lane
          reduction (workloads/cc.py); queries answer component
          label/size/count from the cached index.
khop      k-hop neighborhood count: the MS-BFS core capped at k
          levels, the count read straight from the on-device lane
          summaries — the ``want_distances=False`` fast path
          generalized; ZERO distance words move (workloads/khop.py).
p2p       point-to-point shortest path with bidirectional early
          exit: source and target ride two lanes of one batch, the
          level loop stops the moment the two visited sets meet
          (~half the levels of a full BFS), and the path is
          reconstructed via algorithms/parent_scan (workloads/p2p.py).
========  ==========================================================

The serve tier keys on the axis end to end: ``EngineSpec.kind``
(registry residency + AOT artifact keys), kind-aware batch coalescing
(only same-kind queries share a dispatch), per-kind breaker keys, and
the JSONL protocol's ``"kind"`` field (README "Serving mode").
"""

from __future__ import annotations

import numpy as np

#: Every servable query kind. "bfs" is the default and the only kind the
#: pre-ISSUE-14 protocol knew; requests without a "kind" field mean it.
KINDS = ("bfs", "sssp", "cc", "khop", "p2p")

#: Engine FAMILIES each non-bfs kind can ride; the ``devices`` axis then
#: selects the single-chip or the mesh form within a family (ISSUE 20:
#: every kind runs on the full mesh). The wide family is the common
#: substrate (full-coverage ELL: the CC label fold and the p2p path
#: reconstruction read its row space directly — single-chip AND the
#: sharded dist-wide form; sssp's min-plus tiles reuse its bucket layout
#: on both); khop is pure dispatch/fetch protocol and also runs on the
#: hybrid/packed engines and the 2D partition.
KIND_ENGINES = {
    "bfs": ("wide", "hybrid", "packed", "dist2d"),
    "sssp": ("wide",),
    "cc": ("wide",),
    "khop": ("wide", "hybrid", "packed", "dist2d"),
    "p2p": ("wide",),
}

#: Kinds whose responses never carry (or even compute) a distance table:
#: they answer from on-device summaries / the cached index alone, so the
#: service forces ``want_distances=False`` on them.
METADATA_ONLY_KINDS = ("cc", "khop", "p2p")


def kind_unsupported_reason(kind: str, engine: str, devices: int,
                            graph) -> str | None:
    """WHY this (kind, engine, mesh, graph) combination cannot serve, or
    None when it can — the reason-carrying form of the old silent
    ``continue`` (ISSUE 20 satellite): the serve frontend's unserved-kind
    error names the blocking axis instead of a bare refusal."""
    if kind not in KINDS:
        return f"unknown kind {kind!r} (one of {KINDS})"
    if engine not in KIND_ENGINES[kind]:
        return (
            f"kind {kind!r} rides engine families {KIND_ENGINES[kind]}; "
            f"this service runs engine {engine!r}"
        )
    if engine == "packed" and devices > 1:
        return "the packed engine is single-device (no exchange to shard)"
    if kind == "sssp" and getattr(graph, "weights", None) is None:
        return (
            "sssp relaxes weighted edges and this graph has no weights "
            "plane (generate with weights=W or attach one)"
        )
    if kind == "p2p" and not getattr(graph, "undirected", True):
        # The bidirectional meet is exact on undirected graphs only
        # (the target-side flood must equal the reverse search);
        # P2pServeEngine enforces the same at construction.
        return (
            "p2p's bidirectional meet is exact on undirected graphs "
            "only, and this graph is directed"
        )
    return None


def supported_kinds(engine: str, devices: int, graph) -> tuple:
    """The kinds a service with this engine/mesh/graph can serve — every
    kind :func:`kind_unsupported_reason` has no objection to. Since
    ISSUE 20 the mesh serves every kind (devices > 1 selects the
    distributed form within the same engine family), so the axis that
    used to drop all non-bfs kinds is gone."""
    return tuple(
        kind for kind in KINDS
        if kind_unsupported_reason(kind, engine, devices, graph) is None
    )


def id_of_row_map(engine) -> np.ndarray:
    """[table rows] device-table row -> real vertex id (-1 on pad rows,
    which are never visited), for any full-coverage wide base: the
    single-chip engines expose the ELL's ``old_of_new`` directly; the
    distributed engines' result tables are CHIP-MAJOR over the sharded
    round-robin rank order (chip-major row m = shard ``m // v_loc``'s
    local row ``m % v_loc``, holding global rank
    ``(m % v_loc) * P + m // v_loc``), so the map composes the rank
    inverse with that layout. The CC label fold and the p2p meet-vertex
    lookup both read this one map."""
    ell = getattr(engine, "ell", None)
    if ell is not None:
        return np.asarray(ell.old_of_new[: engine._act], dtype=np.int64)
    sell = engine.sell
    inv = np.full(sell.v_pad, -1, np.int64)
    inv[np.asarray(sell.rank, np.int64)] = np.arange(
        engine.num_vertices, dtype=np.int64
    )
    m = np.arange(sell.v_pad, dtype=np.int64)
    return inv[(m % sell.v_loc) * sell.num_shards + m // sell.v_loc]


def batch_params(queries) -> dict:
    """The batch-uniform dispatch kwargs of one coalesced same-kind batch
    (the scheduler only coalesces queries sharing a ``batch_key``, so the
    first query speaks for all): ``{"k": K}`` for khop, the padded
    ``targets`` array for p2p, ``{}`` otherwise."""
    kind = getattr(queries[0], "kind", "bfs")
    if kind == "khop":
        return {"k": int(queries[0].k)}
    if kind == "p2p":
        return {"targets": np.asarray([int(q.target) for q in queries],
                                      dtype=np.int64)}
    return {}


class ExchangeRecordDelegate:
    """Mixin for adapters over a ``base`` substrate engine: the serve
    executor's wire-telemetry reader and the bench's per-kind wire
    table ride through to the base's exchange record, so a cc/khop/p2p
    query on the mesh prices its batch's exchange bytes exactly like a
    bfs one (single-chip bases record nothing; every reader answers
    None)."""

    def completed_exchange_record(self):
        taker = getattr(self.base, "completed_exchange_record", None)
        if taker is not None:
            return taker()
        return None, getattr(self.base, "last_exchange_bytes", None)

    def wire_bytes_per_level(self):
        fn = getattr(self.base, "wire_bytes_per_level", None)
        return fn() if fn is not None else None

    def exchange_branch_labels(self):
        fn = getattr(self.base, "exchange_branch_labels", None)
        return fn() if fn is not None else None


class ExtrasResult:
    """A batch result wrapper adding per-query ``extras(i)`` response
    fields over an inner result's protocol (reached/ecc/distances) —
    how the khop adapter rides the base engine's own result object."""

    def __init__(self, inner, extras_list):
        self._inner = inner
        self._extras = extras_list

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def extras(self, i: int) -> dict | None:
        return self._extras[i] if i < len(self._extras) else None


class WorkloadResult:
    """A self-contained batch result for adapters that do not delegate to
    a base engine result (cc, p2p): the executor's extraction protocol —
    per-lane ``reached``, on-device-summary ``ecc`` (the ``levels``
    source), optional ``edges_traversed``, per-query ``extras`` — with
    no distance table at all (METADATA_ONLY_KINDS)."""

    def __init__(self, *, reached, ecc, extras_list=None,
                 edges_traversed=None):
        self.reached = np.asarray(reached)
        self.ecc = np.asarray(ecc, dtype=np.int32)
        self.edges_traversed = edges_traversed
        self._extras = extras_list

    def extras(self, i: int) -> dict | None:
        if self._extras is None:
            return None
        return self._extras[i] if i < len(self._extras) else None

    def distances_int32(self, i: int):
        raise ValueError(
            "this workload kind answers from on-device summaries only "
            "(no distance table exists to pull)"
        )


def build_workload_engine(kind: str, base, graph, spec):
    """The serve adapter for ``kind`` over an already-built base engine
    (``base`` is None for sssp, which builds its own weighted substrate).
    Called by the registry's ``_build_inner`` after spec validation."""
    if kind == "sssp":
        devices = int(getattr(spec, "devices", 1))
        if devices > 1:
            # The mesh form (ISSUE 20): sharded min-plus tiles over the
            # 1D ring (or an explicit 2D mesh_shape) with the (min, +)
            # exchange family at bucket close.
            from tpu_bfs.parallel.dist_sssp import DistSsspEngine

            mesh_shape = tuple(getattr(spec, "mesh_shape", ()) or ())
            if mesh_shape:
                from tpu_bfs.parallel.dist_bfs2d import make_mesh_2d

                mesh = make_mesh_2d(*mesh_shape)
            else:
                from tpu_bfs.parallel.dist_bfs import make_mesh

                mesh = make_mesh(devices)
            return DistSsspEngine(
                graph, mesh, lanes=spec.lanes,
                exchange=getattr(spec, "exchange", "") or (
                    "allreduce" if mesh_shape else "ring"
                ),
                delta_bits=tuple(getattr(spec, "delta_bits", ())),
                predict=bool(getattr(spec, "predict", False)),
                expand_impl=getattr(spec, "expand_impl", "xla"),
            )
        from tpu_bfs.workloads.sssp import SsspEngine

        return SsspEngine(
            graph, lanes=spec.lanes,
            expand_impl=getattr(spec, "expand_impl", "xla"),
            overlay=getattr(spec, "overlay", ()),
        )
    if kind == "khop":
        from tpu_bfs.workloads.khop import KhopServeEngine

        return KhopServeEngine(base)
    if kind == "cc":
        from tpu_bfs.workloads.cc import CcServeEngine

        return CcServeEngine(base)
    if kind == "p2p":
        from tpu_bfs.workloads.p2p import P2pServeEngine

        return P2pServeEngine(base)
    raise ValueError(f"unknown workload kind {kind!r} (one of {KINDS})")
