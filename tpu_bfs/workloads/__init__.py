"""tpu_bfs/workloads — the query-kind subsystem over the MS-BFS substrate
(ISSUE 14).

The packed lane machinery (dispatch/fetch protocol, on-device lane
summaries, width ladder, fuzz oracle) is a general multi-source traversal
substrate; this package widens what it answers from one query type to
five, each served through the same coalescing/ladder/OOM/breaker path
behind a ``"kind"`` axis:

========  ==========================================================
kind      semantics (and substrate)
========  ==========================================================
bfs       single-source BFS distances — the original query (the base
          engines themselves; no adapter).
sssp      single-source shortest paths over the WEIGHTED graph:
          bucketed delta-stepping on int32 tentative distances over
          the same ELL tiles (workloads/sssp.py), light edges relaxed
          to a fixed point per bucket, heavy edges once at bucket
          close — Buluç & Madduri's framing of SSSP as the same
          frontier-expansion kernel as BFS (arXiv:1104.4518).
cc        connected components: repeated MS-BFS sweeps with lane
          recycling (finished lanes re-seeded from the unvisited
          set), per-row labels folded ON DEVICE via a min-lane
          reduction (workloads/cc.py); queries answer component
          label/size/count from the cached index.
khop      k-hop neighborhood count: the MS-BFS core capped at k
          levels, the count read straight from the on-device lane
          summaries — the ``want_distances=False`` fast path
          generalized; ZERO distance words move (workloads/khop.py).
p2p       point-to-point shortest path with bidirectional early
          exit: source and target ride two lanes of one batch, the
          level loop stops the moment the two visited sets meet
          (~half the levels of a full BFS), and the path is
          reconstructed via algorithms/parent_scan (workloads/p2p.py).
========  ==========================================================

The serve tier keys on the axis end to end: ``EngineSpec.kind``
(registry residency + AOT artifact keys), kind-aware batch coalescing
(only same-kind queries share a dispatch), per-kind breaker keys, and
the JSONL protocol's ``"kind"`` field (README "Serving mode").
"""

from __future__ import annotations

import numpy as np

#: Every servable query kind. "bfs" is the default and the only kind the
#: pre-ISSUE-14 protocol knew; requests without a "kind" field mean it.
KINDS = ("bfs", "sssp", "cc", "khop", "p2p")

#: Engines each non-bfs kind can ride. The wide engine is the common
#: substrate (full-coverage ELL: the CC label fold and the p2p path
#: reconstruction read its row space directly; the SSSP tiles reuse its
#: bucket layout); khop is pure dispatch/fetch protocol and also runs on
#: the hybrid/packed engines. All non-bfs kinds are single-chip in this
#: PR (devices == 1) — the mesh generalization rides ROADMAP item 1's
#: partitioned substrate.
KIND_ENGINES = {
    "bfs": ("wide", "hybrid", "packed", "dist2d"),
    "sssp": ("wide",),
    "cc": ("wide",),
    "khop": ("wide", "hybrid", "packed"),
    "p2p": ("wide",),
}

#: Kinds whose responses never carry (or even compute) a distance table:
#: they answer from on-device summaries / the cached index alone, so the
#: service forces ``want_distances=False`` on them.
METADATA_ONLY_KINDS = ("cc", "khop", "p2p")


def supported_kinds(engine: str, devices: int, graph) -> tuple:
    """The kinds a service with this engine/mesh/graph can serve: every
    kind whose engine family matches, minus sssp when the graph has no
    weights plane."""
    out = []
    for kind in KINDS:
        if engine not in KIND_ENGINES[kind]:
            continue
        if kind != "bfs" and devices > 1:
            continue
        if kind == "sssp" and getattr(graph, "weights", None) is None:
            continue
        if kind == "p2p" and not getattr(graph, "undirected", True):
            # The bidirectional meet is exact on undirected graphs only
            # (the target-side flood must equal the reverse search);
            # P2pServeEngine enforces the same at construction.
            continue
        out.append(kind)
    return tuple(out)


def batch_params(queries) -> dict:
    """The batch-uniform dispatch kwargs of one coalesced same-kind batch
    (the scheduler only coalesces queries sharing a ``batch_key``, so the
    first query speaks for all): ``{"k": K}`` for khop, the padded
    ``targets`` array for p2p, ``{}`` otherwise."""
    kind = getattr(queries[0], "kind", "bfs")
    if kind == "khop":
        return {"k": int(queries[0].k)}
    if kind == "p2p":
        return {"targets": np.asarray([int(q.target) for q in queries],
                                      dtype=np.int64)}
    return {}


class ExtrasResult:
    """A batch result wrapper adding per-query ``extras(i)`` response
    fields over an inner result's protocol (reached/ecc/distances) —
    how the khop adapter rides the base engine's own result object."""

    def __init__(self, inner, extras_list):
        self._inner = inner
        self._extras = extras_list

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def extras(self, i: int) -> dict | None:
        return self._extras[i] if i < len(self._extras) else None


class WorkloadResult:
    """A self-contained batch result for adapters that do not delegate to
    a base engine result (cc, p2p): the executor's extraction protocol —
    per-lane ``reached``, on-device-summary ``ecc`` (the ``levels``
    source), optional ``edges_traversed``, per-query ``extras`` — with
    no distance table at all (METADATA_ONLY_KINDS)."""

    def __init__(self, *, reached, ecc, extras_list=None,
                 edges_traversed=None):
        self.reached = np.asarray(reached)
        self.ecc = np.asarray(ecc, dtype=np.int32)
        self.edges_traversed = edges_traversed
        self._extras = extras_list

    def extras(self, i: int) -> dict | None:
        if self._extras is None:
            return None
        return self._extras[i] if i < len(self._extras) else None

    def distances_int32(self, i: int):
        raise ValueError(
            "this workload kind answers from on-device summaries only "
            "(no distance table exists to pull)"
        )


def build_workload_engine(kind: str, base, graph, spec):
    """The serve adapter for ``kind`` over an already-built base engine
    (``base`` is None for sssp, which builds its own weighted substrate).
    Called by the registry's ``_build_inner`` after spec validation."""
    if kind == "sssp":
        from tpu_bfs.workloads.sssp import SsspEngine

        return SsspEngine(
            graph, lanes=spec.lanes,
            expand_impl=getattr(spec, "expand_impl", "xla"),
            overlay=getattr(spec, "overlay", ()),
        )
    if kind == "khop":
        from tpu_bfs.workloads.khop import KhopServeEngine

        return KhopServeEngine(base)
    if kind == "cc":
        from tpu_bfs.workloads.cc import CcServeEngine

        return CcServeEngine(base)
    if kind == "p2p":
        from tpu_bfs.workloads.p2p import P2pServeEngine

        return P2pServeEngine(base)
    raise ValueError(f"unknown workload kind {kind!r} (one of {KINDS})")
