"""Connected components via repeated MS-BFS sweeps with lane recycling
(ISSUE 14).

One MS-BFS sweep floods up to ``lanes`` components at once; lanes whose
seeds share a component flood the same vertex set. The driver recycles
finished lanes by re-seeding each next sweep from the still-unvisited
set (ascending vertex id, so a component's label is the smallest seed
that ever flooded it) until every vertex is labeled. Per sweep the
per-row label fold runs ON DEVICE: a min-lane reduction over the visited
bit table ([rows, w] uint32 -> [rows] int32 smallest visiting lane),
one [act] transfer per sweep instead of decoding lane bits host-side.

Undirected graphs only define the classic notion; on the repo's directed
inputs the sweep computes reachability-closure classes of the seed order
(documented, matching what repeated BFS gives — the fuzz oracle compares
against ``scipy.sparse.csgraph.connected_components`` on undirected
graphs).

The serve adapter caches the index per engine residency: the first
dispatch (or the registry's warm-up) pays the sweeps; every query after
answers component label / size / total count from host arrays.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from tpu_bfs.workloads import (
    ExchangeRecordDelegate,
    WorkloadResult,
    id_of_row_map,
)

_NO_LANE = np.int32(1 << 30)


def _make_min_lane(rows: int, act: int, w: int):
    """[rows, w] visited table -> [act] smallest visiting lane (word-major
    lane map, the wide engine's), _NO_LANE where no lane visited."""

    @jax.jit
    def min_lane(vis):
        if act == 0:
            return jnp.zeros((0,), jnp.int32)
        shifts = jnp.arange(32, dtype=jnp.uint32)

        def wbody(wi, acc):
            col = jax.lax.dynamic_slice(vis, (0, wi), (rows, 1))[:act]
            bits = ((col >> shifts) & 1) != 0  # [act, 32]
            lid = wi * 32 + jnp.arange(32, dtype=jnp.int32)
            cand = jnp.min(
                jnp.where(bits, lid[None, :], _NO_LANE), axis=1
            )
            return jnp.minimum(acc, cand)

        return jax.lax.fori_loop(
            0, w, wbody, jnp.full((act,), _NO_LANE, jnp.int32)
        )

    return min_lane


def connected_components(engine, *, max_sweeps: int | None = None):
    """Full component labeling over ``engine``'s graph (a wide packed MS
    engine). Returns ``(labels [V] int64, num_components, sweeps)`` —
    ``labels[v]`` is the smallest vertex id that seeded v's component's
    flood (a canonical representative under the ascending re-seed
    order)."""
    V = engine.num_vertices
    act = engine._act
    # Table geometry is engine-shaped: single-chip result tables carry
    # the ELL sentinel row (act + 1 rows, id map = old_of_new); the
    # distributed wide engine's are sentinel-free chip-major v_pad rows
    # (ISSUE 20 — the same sweep labels across the mesh). Pad rows map
    # to -1 and are never visited, but guard anyway.
    rows = int(getattr(engine, "_table_rows", act + 1))
    min_lane = _make_min_lane(rows, act, engine.w)
    id_of_row = id_of_row_map(engine)
    labels = np.full(V, -1, np.int64)
    unseen = np.ones(V, dtype=bool)
    sweeps = 0
    cap = max_sweeps if max_sweeps is not None else V + 1
    while sweeps < cap:
        pending = np.flatnonzero(unseen)
        if not len(pending):
            break
        seeds = pending[: engine.lanes]
        res = engine.run(seeds, time_it=False)
        ml = np.asarray(min_lane(res._vis))
        hit = (ml < _NO_LANE) & (id_of_row >= 0)
        vids = id_of_row[hit]
        labels[vids] = seeds[ml[hit]]
        unseen[vids] = False
        # Lane recycling: isolated seeds (no table row — their component
        # is themselves) and any seed the fold missed label themselves;
        # every seed lane is finished and free for the next sweep.
        self_label = labels[seeds] < 0
        labels[seeds[self_label]] = seeds[self_label]
        unseen[seeds] = False
        sweeps += 1
    if unseen.any():
        raise RuntimeError(
            f"cc sweeps did not converge in {sweeps} sweeps "
            f"({int(unseen.sum())} vertices unlabeled)"
        )
    num_components = len(np.unique(labels))
    return labels, num_components, sweeps


class CcIndex:
    """The cached component index one labeling produces."""

    def __init__(self, labels: np.ndarray, num_components: int, sweeps: int):
        self.labels = labels
        self.num_components = num_components
        self.sweeps = sweeps
        uniq, inv, counts = np.unique(
            labels, return_inverse=True, return_counts=True
        )
        self.size_of = counts[inv]  # [V] component size per vertex


class CcServeEngine(ExchangeRecordDelegate):
    """Serve adapter: kind="cc" queries answer component label / size /
    total count from the cached index (built on first use — the
    registry's warm-up run, so serving queries never pay the sweeps)."""

    kind = "cc"

    def __init__(self, base):
        self.base = base
        self.lanes = base.lanes
        self.num_vertices = base.num_vertices
        self._lock = threading.Lock()
        self._index: CcIndex | None = None  # guarded-by: _lock

    def _ensure_index(self) -> CcIndex:
        with self._lock:
            if self._index is None:
                labels, n, sweeps = connected_components(self.base)
                self._index = CcIndex(labels, n, sweeps)
            return self._index

    def set_overlay(self, tables) -> None:
        """Dynamic-graph flip (ISSUE 19): swap the overlay on the base
        sweep engine AND drop the cached component index — the labels
        were computed over the pre-mutation edge set, and an edge can
        merge or (via removal) split components. The next cc query pays
        the re-label sweeps over the folded graph."""
        self.base.set_overlay(tables)
        with self._lock:
            self._index = None

    def dispatch(self, sources, **_ignored) -> np.ndarray:
        return np.asarray(sources, dtype=np.int64)

    def fetch(self, sources: np.ndarray, **_ignored) -> WorkloadResult:
        idx = self._ensure_index()
        labels = idx.labels[sources]
        sizes = idx.size_of[sources]
        extras = [
            {
                "component": int(lbl),
                "component_size": int(sz),
                "components": idx.num_components,
            }
            for lbl, sz in zip(labels, sizes)
        ]
        return WorkloadResult(
            reached=sizes.astype(np.int64),
            ecc=np.zeros(len(sources), np.int32),
            extras_list=extras,
        )

    def run(self, sources, *, time_it: bool = False, **_ignored):
        return self.fetch(self.dispatch(sources))

    def analysis_programs(self):
        """Static-analyzer hook: the on-device label fold (min-lane
        reduction) over an example visited table."""
        import numpy as np

        base = self.base
        rows = int(getattr(base, "_table_rows", base._act + 1))
        ml = _make_min_lane(rows, base._act, base.w)
        # Trim the seed table to the RESULT-table row count the sweeps
        # feed (the dist-wide seed carries a sentinel row its chip-major
        # result tables do not), so the analyzed shape is the served one.
        vis0 = base._seed_dev(np.asarray([0]))[:rows]
        return [("cc_min_lane", ml, (vis0,))]
