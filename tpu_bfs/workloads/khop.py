"""k-hop neighborhood counts straight off the on-device lane summaries
(ISSUE 14).

A k-hop query is the MS-BFS core capped at ``max_levels=k``: after k
level bodies the visited table holds exactly the vertices within k hops,
and the engines' on-device ``lane_stats`` reduction already counts them
per lane — so the answer is the existing ``reached`` summary with ZERO
distance words pulled (the ``want_distances=False`` fast path, now the
whole query). The adapter is pure dispatch/fetch protocol, so it rides
any packed MS engine (wide / hybrid / packed).

Batches only coalesce same-k queries (the scheduler's batch key carries
k), so one dispatch's level bound answers every lane.
"""

from __future__ import annotations

from tpu_bfs.workloads import ExchangeRecordDelegate, ExtrasResult


class KhopServeEngine(ExchangeRecordDelegate):
    """Serve adapter: kind="khop" over a base packed MS engine."""

    kind = "khop"

    def __init__(self, base):
        self.base = base
        self.lanes = base.lanes
        self.num_vertices = base.num_vertices

    def set_overlay(self, tables) -> None:
        """Dynamic-graph flip (ISSUE 19): pure delegation — the adapter
        caches nothing derived from the edge set (counts come off the
        base engine's per-run lane summaries)."""
        self.base.set_overlay(tables)

    def dispatch(self, sources, *, k: int = 1, **_ignored):
        k = int(k)
        if k < 0:
            raise ValueError(f"k must be >= 0, got {k}")
        # A k at/above the plane cap clamps to it; fetch's cap check
        # (below) then raises if the traversal was actually cut off, so
        # the clamp can never silently undercount.
        kk = min(k, self.base.max_levels_cap)
        return self.base.dispatch(sources, max_levels=kk), k

    def fetch(self, handle, **_ignored) -> ExtrasResult:
        pend, k = handle
        # check_cap=True is exactly the right guard here: for k below
        # the plane cap it is a no-op (stopping AT the bound is this
        # query's point — the base only flags truncation when the bound
        # IS the cap), while a clamped k >= cap on a graph deeper than
        # the cap raises instead of reporting the cap-radius ball as
        # the k-hop count.
        res = self.base.fetch(pend, check_cap=True)
        n = len(res.sources)
        extras = [{"k": k} for _ in range(n)]
        return ExtrasResult(res, extras)

    def run(self, sources, *, k: int = 1, time_it: bool = False,
            **_ignored) -> ExtrasResult:
        return self.fetch(self.dispatch(sources, k=k))

    def analysis_programs(self):
        """Static-analyzer hook (tpu_bfs/analysis): the base core under
        a finite hop bound — the exact program a khop dispatch runs
        (``max_levels`` is a traced scalar, so this IS the bfs core; the
        sweep proves the kind adds no new compiled surface)."""
        import jax.numpy as jnp
        import numpy as np

        base = self.base
        if getattr(base, "pull_gate", False):
            return []
        if hasattr(base, "_dist_core") or not hasattr(base, "_core"):
            # Distributed bases (ISSUE 20): their ``_core`` is a host
            # wrapper (or absent on the dist2d serve adapter), and the
            # hop bound is the same traced max_levels scalar of the
            # sharded loop — delegate to the base's own analyzed
            # programs, relabeled so the khop config's entries stay
            # distinct in the sweep.
            return [
                (f"khop_{name}", fn, args)
                for name, fn, args in base.analysis_programs()
            ]
        fw0 = base._seed_dev(np.asarray([0]))
        return [("khop_core", base._core, (base.arrs, fw0, jnp.int32(2)))]
