"""Landmark distance tier: p2p answers without traversing (ISSUE 18).

The MS-BFS substrate runs thousands of sources per batch, so K extra
sources at warm-up are nearly free — landmarks are just lanes. One
flagship batch from the K highest-degree vertices yields distance
columns ``d(l, v)`` for every landmark ``l`` and vertex ``v``; on an
undirected graph the triangle inequality then brackets any pairwise
distance:

    max_l |d(l,s) - d(l,t)|  <=  d(s,t)  <=  min_l d(l,s) + d(l,t)

When the bounds meet the answer is EXACT and a p2p query resolves in
microseconds of NumPy indexing instead of a traversal. High-degree
landmarks make the bounds tight exactly where Zipfian traffic lands:
hub-adjacent pairs route through a landmark, collapsing the bracket.
The serve tier only ever returns exact landmark answers — a bounded
bracket is recorded (``landmark_bounded``) and the query falls back to
traversal, so armed-vs-off streams stay bit-identical.

Reachability is part of the contract: with one landmark per connected
component (high-degree selection gets there fast on real graphs), a
pair split across components shows one finite and one infinite column
entry for some landmark, which proves ``d(s,t) = INF`` exactly.
Both-infinite columns prove nothing and contribute no bound.

Directed graphs are gated off (like the p2p workload itself): the
symmetric triangle bound needs ``d(l,s) = d(s,l)``.

Columns are written once by :meth:`LandmarkIndex.warm` (the serve
warm-up path, under an obs span) and read lock-free afterwards; only
the hit counters take the lock.
"""

from __future__ import annotations

import threading

import numpy as np

from tpu_bfs import obs as _obs
from tpu_bfs.graph.csr import INF_DIST

#: Python-int unreachable sentinel used in bounds (int64 math: the
#: int32 INF would overflow in ``d(l,s) + d(l,t)``).
INF = int(INF_DIST)

#: Default landmark count: one flagship batch column per hub. 16 keeps
#: warm-up inside a single lane group on every ladder width.
DEFAULT_K = 16


def select_landmarks(graph, k: int) -> np.ndarray:
    """Top-``k`` vertices by degree, ties broken by vertex id (so the
    selection — and therefore every bound — is deterministic across
    processes)."""
    n = graph.num_vertices
    k = max(1, min(int(k), n))
    deg = graph.degrees
    order = np.lexsort((np.arange(n), -deg))
    return np.sort(order[:k]).astype(np.int64)


class LandmarkIndex:
    """K distance columns + the triangle-bound query path. Build with
    the host graph, then :meth:`warm` with a batch runner before the
    first :meth:`answer`."""

    def __init__(self, graph, k: int = DEFAULT_K, *, metrics=None):
        if not graph.undirected:
            raise ValueError(
                "landmark bounds need an undirected graph (d(l,s) must "
                "equal d(s,l)); directed graphs fall back to traversal"
            )
        self.landmarks = select_landmarks(graph, k)
        self.k = len(self.landmarks)
        self.num_vertices = graph.num_vertices
        self.metrics = metrics
        self._lock = threading.Lock()
        self._columns = None  # (K, V) int64; written ONCE by warm()
        self._warm_ms = 0.0
        self._exact = 0  # guarded-by: _lock
        self._bounded = 0  # guarded-by: _lock
        self._fallback = 0  # guarded-by: _lock
        self._invalidations = 0  # guarded-by: _lock

    @property
    def warmed(self) -> bool:
        return self._columns is not None

    def invalidate(self) -> None:
        """Dynamic-graph flip (ISSUE 19): drop the distance columns —
        they were computed over the pre-mutation edge set, and a single
        added edge can tighten d(l, v) everywhere, so every triangle
        bound (including "exact" ones) is suspect. The tier answers
        nothing until the owner re-warms it over the folded graph; the
        fix for the frozen-at-warm-up staleness hole this tier shipped
        with."""
        with self._lock:
            self._columns = None
            self._invalidations += 1

    # --- warm-up ----------------------------------------------------------

    def warm(self, run_batch) -> float:
        """Compute the K distance columns with ONE flagship batch.
        ``run_batch(sources)`` is any MS-BFS runner returning a result
        with ``distances_int32(i)`` per lane (engine.run wrapped by the
        caller). Returns the warm-up wall time in milliseconds."""
        import time

        rec = _obs.ACTIVE
        if rec is not None:
            rec.begin("landmark_warm", "landmarks", cat="serve.cache",
                      k=self.k)
        t0 = time.monotonic()
        try:
            res = run_batch(self.landmarks)
            cols = np.stack(
                [np.asarray(res.distances_int32(i), dtype=np.int64)
                 for i in range(self.k)]
            )
            if cols.shape != (self.k, self.num_vertices):
                raise ValueError(
                    f"landmark warm-up returned columns of shape "
                    f"{cols.shape}, wanted {(self.k, self.num_vertices)}"
                )
            self._columns = cols
            self._warm_ms = (time.monotonic() - t0) * 1e3
            return self._warm_ms
        finally:
            if rec is not None:
                rec.end("landmark_warm", "landmarks", cat="serve.cache",
                        warmed=self._columns is not None)

    # --- queries ----------------------------------------------------------

    def bounds(self, s: int, t: int) -> tuple[int, int, bool]:
        """Triangle-bound bracket ``(lo, hi, exact)`` on ``d(s, t)``,
        with ``(INF, INF, True)`` proving unreachability. ``exact`` iff
        ``lo == hi``; with no informative landmark the vacuous
        ``(0, INF, False)`` comes back."""
        if self._columns is None:
            raise RuntimeError("LandmarkIndex.bounds before warm()")
        if s == t:
            return 0, 0, True
        ds = self._columns[:, s]
        dt = self._columns[:, t]
        fs = ds != INF
        ft = dt != INF
        # One side reachable from l, the other not: different components.
        if bool(np.any(fs != ft)):
            return INF, INF, True
        both = fs & ft
        if not bool(np.any(both)):
            return 0, INF, False
        ds = ds[both]
        dt = dt[both]
        lo = int(np.max(np.abs(ds - dt)))
        hi = int(np.min(ds + dt))
        return lo, hi, lo == hi

    def answer_p2p(self, s: int, t: int):
        """The serve-path consult: an EXACT p2p extras payload, or None
        when only a bracket (or nothing) is known and the query must
        fall back to traversal. Counts exact/bounded/fallback either
        way."""
        lo, hi, exact = self.bounds(s, t)
        if exact:
            self._count("_exact")
            if self.metrics is not None:
                self.metrics.record_landmark(exact=True)
            met = hi != INF
            return {
                "target": int(t),
                "met": met,
                "distance": int(hi) if met else None,
                "path": None,
                "exact": True,
                "landmark": True,
            }
        informative = lo > 0 or hi != INF
        self._count("_bounded" if informative else "_fallback")
        if self.metrics is not None:
            self.metrics.record_landmark(exact=False,
                                         informative=informative)
        return None

    def _count(self, field: str) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)

    # --- introspection ----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "k": self.k,
                "warmed": self.warmed,
                "warm_ms": round(self._warm_ms, 3),
                "exact": self._exact,
                "bounded": self._bounded,
                "fallback": self._fallback,
                "invalidations": self._invalidations,
            }

    def config_summary(self) -> dict:
        out = self.stats()
        out["landmarks"] = [int(v) for v in self.landmarks[:8]]
        return out
