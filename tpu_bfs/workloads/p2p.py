"""Point-to-point shortest path with bidirectional early exit (ISSUE 14).

A p2p query (s, t) rides TWO adjacent lanes of one packed batch: lane 2i
floods from s, lane 2i+1 from t (undirected graphs — the repo's
double-insert representation — make the reverse search the same
expansion). The level loop advances ONE level per step through the base
engine's resumable core (``_core_from``, the checkpoint entry — carries
stay on device between steps) and stops the moment every pair's two
visited sets intersect: if D = d(s, t), the meet happens after
ceil(D / 2) levels, and the answer is EXACT at that point — every meet
vertex v satisfies d_s(v) + d_t(v) >= D, while some vertex on a shortest
path lands in the intersection with equality the moment it is nonempty
(both searches ran L levels, so intersection nonempty implies D <= 2L,
which puts a path midpoint inside both balls). So the loop expands
~half the frontier levels a full single-source BFS would (strictly
fewer whenever D >= 2 — the fuzz bar), and the serve response's
``levels`` field reports the levels actually expanded.

The meet check per level is one tiny on-device kernel over the visited
words (no distance decode); the final per-pair distance/meet-vertex
reduction decodes the bit-sliced planes once, on device. The path is
reconstructed from the two lanes' deterministic min-parent trees
(algorithms/parent_scan via PackedBatchResult.parents_int32) and
validated edge-by-edge by the fuzz oracle.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from tpu_bfs.algorithms._packed_common import _assemble_packed_result
from tpu_bfs.workloads import ExchangeRecordDelegate, id_of_row_map

#: "No meet" distance sentinel: far above any labelable distance (the
#: plane cap is 254) and safe to double without overflow.
_BIG = np.int32(1 << 20)


def _make_pair_kernels(rows: int, act: int, w: int, num_planes: int):
    """(pair_met, pair_dist) over the wide engine's word-major tables.

    ``pair_met(vis) -> [w*16] bool``: pair p (lanes 2p, 2p+1) has a row
    both lanes visited. ``pair_dist(planes, vis, src_bits) ->
    (dist [w*16] i32, row [w*16] i32)``: min over rows of
    d_s(row) + d_t(row) (both-visited rows only; _BIG-based when unmet)
    and the argmin row — the meet vertex."""
    npairs = w * 16
    shifts = jnp.arange(32, dtype=jnp.uint32)

    @jax.jit
    def pair_met(vis):
        if act == 0:
            return jnp.zeros((npairs,), bool)

        def wbody(wi, acc):
            col = jax.lax.dynamic_slice(vis, (0, wi), (rows, 1))[:act]
            bits = ((col >> shifts) & 1) != 0  # [act, 32]
            both = jnp.any(bits[:, 0::2] & bits[:, 1::2], axis=0)  # [16]
            return jax.lax.dynamic_update_slice(acc, both, (wi * 16,))

        return jax.lax.fori_loop(
            0, w, wbody, jnp.zeros((npairs,), bool)
        )

    @jax.jit
    def pair_dist(planes, vis, src_bits):
        if act == 0:
            return (
                jnp.full((npairs,), 2 * _BIG, jnp.int32),
                jnp.zeros((npairs,), jnp.int32),
            )

        def wbody(wi, acc):
            dmin, rmin = acc
            cnt = jnp.zeros((act, 32), jnp.int32)
            for i, p in enumerate(planes):
                col = jax.lax.dynamic_slice(p, (0, wi), (rows, 1))[:act]
                cnt = cnt + (((col >> shifts) & 1) << i).astype(jnp.int32)
            visw = (
                (jax.lax.dynamic_slice(vis, (0, wi), (rows, 1))[:act]
                 >> shifts) & 1
            ) != 0
            srcw = (
                (jax.lax.dynamic_slice(src_bits, (0, wi), (rows, 1))[:act]
                 >> shifts) & 1
            ) != 0
            d = jnp.where(srcw, 0, jnp.where(visw, cnt + 1, _BIG))
            s = d[:, 0::2] + d[:, 1::2]  # [act, 16]
            smin = jnp.min(s, axis=0)
            srow = jnp.argmin(s, axis=0).astype(jnp.int32)
            return (
                jax.lax.dynamic_update_slice(dmin, smin, (wi * 16,)),
                jax.lax.dynamic_update_slice(rmin, srow, (wi * 16,)),
            )

        return jax.lax.fori_loop(
            0, w, wbody,
            (jnp.full((npairs,), 2 * _BIG, jnp.int32),
             jnp.zeros((npairs,), jnp.int32)),
        )

    return pair_met, pair_dist


class P2pPending:
    """A dispatched (seeded, not yet stepped) bidirectional batch."""

    __slots__ = ("sources", "targets", "inter", "fw0", "n")

    def __init__(self, sources, targets, inter, fw0):
        self.sources = sources
        self.targets = targets
        self.inter = inter
        self.fw0 = fw0
        self.n = len(sources)


class P2pResult:
    """Per-pair outcomes with path reconstruction baked in.

    ``ecc`` carries the LEVELS EXPANDED (same for every pair of the
    batch) — the serve response's ``levels`` field, the number a full
    single-source BFS strictly exceeds whenever d(s, t) >= 2."""

    def __init__(self, *, reached, levels_expanded, extras_list):
        n = len(extras_list)
        self.reached = np.asarray(reached, dtype=np.int64)
        self.ecc = np.full(n, int(levels_expanded), np.int32)
        self.edges_traversed = None
        self._extras = extras_list

    def extras(self, i: int) -> dict | None:
        return self._extras[i] if i < len(self._extras) else None

    def distances_int32(self, i: int):
        raise ValueError("p2p answers carry the path, not a distance table")


class P2pServeEngine(ExchangeRecordDelegate):
    """Serve adapter: kind="p2p" over a base WIDE packed MS engine.

    ``lanes`` here counts PAIRS — half the base engine's lane budget —
    so the executor's padding and the service's routing stay in query
    units."""

    kind = "p2p"

    def __init__(self, base):
        if getattr(base, "pull_gate", False):
            raise ValueError(
                "p2p drives the resumable core level by level; the pull "
                "gate's batch-scoped lane mask does not compose with "
                "that (build the base engine ungated)"
            )
        if not base.undirected:
            raise ValueError(
                "p2p's bidirectional meet is exact on undirected graphs "
                "only (the target-side flood must equal the reverse "
                "search); serve directed graphs without the p2p kind"
            )
        self.base = base
        self.pairs = base.lanes // 2
        if self.pairs < 1:
            raise ValueError(
                "p2p needs a base engine of >= 2 lanes (one pair)"
            )
        self.lanes = self.pairs
        # Bookkeeping width: the ladder/breaker/OOM-degrade machinery
        # operates in BASE lane units (the registry spec's width); this
        # adapter's ``lanes`` counts PAIRS (batch capacity), so it
        # publishes the base width separately or a p2p failure would
        # feed the wrong rung's breaker and over-degrade the service.
        self.ladder_lanes = base.lanes
        self.num_vertices = base.num_vertices
        # Engine-shaped table geometry (ISSUE 20): single-chip tables
        # carry the sentinel row and map rows through the ELL; the
        # distributed wide base's are sentinel-free chip-major — one
        # shared id map covers both (workloads.id_of_row_map).
        self._id_of_row = id_of_row_map(base)
        self._table_rows = int(getattr(base, "_table_rows", base._act + 1))
        self._pair_met, self._pair_dist = _make_pair_kernels(
            self._table_rows, base._act, base.w, base.num_planes
        )

    def warm_residency(self) -> None:
        """Registry warm-up hook (ROADMAP item 3b): build and cache the
        base engine's device parent scanner now, while the residency is
        being warmed, so the FIRST p2p path reconstruction runs the
        cached-scanner fast path instead of paying a cold O(E) host
        scatter-min per lane. The wide base engine's scanner BORROWS its
        existing ELL arrays (zero extra HBM — parent_scanner_of's
        caching policy); unavailability is cached too, so this is a
        no-op on engines that cannot scan."""
        from tpu_bfs.algorithms._packed_common import parent_scanner_of

        parent_scanner_of(self.base)

    def dispatch(self, sources, *, targets=None, **_ignored) -> P2pPending:
        sources = np.asarray(sources, dtype=np.int64)
        if targets is None:
            # Warm-up / convenience default: a fixed non-trivial target
            # per lane so the level loop actually compiles and steps.
            targets = (sources + 1) % self.num_vertices
        targets = np.asarray(targets, dtype=np.int64)
        if sources.shape != targets.shape or sources.ndim != 1:
            raise ValueError("sources/targets must be equal-length 1-D")
        if not (1 <= len(sources) <= self.pairs):
            raise ValueError(
                f"need 1..{self.pairs} pairs, got {len(sources)}"
            )
        for arr, what in ((sources, "source"), (targets, "target")):
            if len(arr) and (arr.min() < 0 or arr.max() >= self.num_vertices):
                raise ValueError(f"{what} out of range")
        inter = np.empty(2 * len(sources), dtype=np.int64)
        inter[0::2] = sources
        inter[1::2] = targets
        fw0 = self.base._seed_dev(inter)
        return P2pPending(sources, targets, inter, fw0)

    def fetch(self, pend: P2pPending, **_ignored) -> P2pResult:
        base = self.base
        n = pend.n
        fw = pend.fw0
        # The resumable core's visited/planes carry rides the RESULT
        # table layout; the dist-wide base's differs from its rank-order
        # seed table (chip-major, no sentinel row) and provides the
        # converting view — the same view _assemble_packed_result takes
        # for the src-bits plane.
        view = getattr(base, "_src_bits_view", None)
        vis = src_view = view(pend.fw0) if view is not None else pend.fw0
        planes = tuple(jnp.zeros_like(vis)
                       for _ in range(base.num_planes))
        level = 0
        alive = True
        # Level 0 can already be met (s == t, or s adjacent... no — only
        # s == t: the visited sets start as the endpoints themselves).
        met = np.asarray(self._pair_met(vis))[: n]
        while not met.all() and alive and level < base.max_levels_cap:
            fw, vis, planes, lv, alv = base._core_from(
                base.arrs, fw, vis, planes, jnp.int32(level),
                jnp.int32(level + 1),
            )
            level = int(lv)
            alive = bool(alv)
            met = np.asarray(self._pair_met(vis))[: n]
        dist, row = self._pair_dist(planes, vis, src_view)
        dist = np.asarray(dist)[: n]
        row = np.asarray(row)[: n]
        iso = base._iso_of(pend.inter)
        res = _assemble_packed_result(
            base, pend.inter, planes, vis, pend.fw0, level, alive, None,
        )
        extras = []
        reached = np.empty(n, np.int64)
        for i in range(n):
            s, t = int(pend.sources[i]), int(pend.targets[i])
            reached[i] = int(res.reached[2 * i]) + int(res.reached[2 * i + 1])
            if iso is not None and (iso[2 * i] or iso[2 * i + 1]):
                # An isolated endpoint reaches nothing beyond itself.
                found = s == t
                extras.append({
                    "target": t, "met": found,
                    "distance": 0 if found else None,
                    "path": [s] if found else None,
                })
                continue
            if s == t:
                extras.append({
                    "target": t, "met": True, "distance": 0, "path": [s],
                })
                continue
            if dist[i] >= _BIG:
                extras.append({
                    "target": t, "met": False, "distance": None,
                    "path": None,
                })
                continue
            vmeet = int(self._id_of_row[row[i]])
            path = self._reconstruct(res, i, s, t, vmeet)
            extras.append({
                "target": t, "met": True, "distance": int(dist[i]),
                "path": path,
            })
        return P2pResult(
            reached=reached, levels_expanded=level, extras_list=extras,
        )

    def _reconstruct(self, res, i: int, s: int, t: int, vmeet: int):
        """s -> meet -> t through the two lanes' deterministic min-parent
        trees (parent_scan / host scatter-min — both bit-equal)."""
        par_s = res.parents_int32(2 * i)
        par_t = res.parents_int32(2 * i + 1)
        half_s = _walk_to_root(par_s, vmeet, s)
        half_t = _walk_to_root(par_t, vmeet, t)
        if half_s is None or half_t is None:
            return None  # defensive: a met pair always walks clean
        return list(reversed(half_s)) + half_t[1:]

    def run(self, sources, *, targets=None, time_it: bool = False,
            **_ignored) -> P2pResult:
        return self.fetch(self.dispatch(sources, targets=targets))

    def analysis_programs(self):
        """Static-analyzer hook: the per-level meet check and the final
        per-pair distance/meet-vertex reduction."""
        base = self.base
        fw0 = base._seed_dev(np.asarray([0, 1]))
        # Same layout conversion as fetch: analyze the RESULT-table shape
        # the serving loop actually feeds the kernels.
        view = getattr(base, "_src_bits_view", None)
        vis0 = view(fw0) if view is not None else fw0
        planes0 = tuple(
            jnp.zeros_like(vis0) for _ in range(base.num_planes)
        )
        return [
            ("p2p_pair_met", self._pair_met, (vis0,)),
            ("p2p_pair_dist", self._pair_dist, (planes0, vis0, vis0)),
        ]


def _walk_to_root(parent: np.ndarray, frm: int, root: int):
    """Parent-pointer walk frm -> root; None if the chain breaks."""
    path = [frm]
    v = frm
    for _ in range(len(parent)):
        if v == root:
            return path
        v = int(parent[v])
        if v < 0:
            return None
        path.append(v)
    return None
