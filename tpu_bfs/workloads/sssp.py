"""Bucketed delta-stepping SSSP over the ELL tiles (ISSUE 14).

The same degree-sorted bucketed-ELL layout the packed BFS engines expand
(graph/ell.py) runs MIN-PLUS instead of OR: out[v] = min over in-edges
(u, v) of dist[u] + w(u, v). Bitwise-OR over packed lane words becomes
elementwise minimum over an int32 tentative-distance table [rows, L]
(one column per SSSP lane; L is small — each lane costs 32x a BFS lane's
bits), and the weights plane (graph/ell.build_ell_weights) rides the
bucket tables slot-for-slot. The heavy fold pyramid works unchanged —
min is associative-commutative with identity INF, the two properties the
pyramid assumes (see make_fori_expand's combine/identity contract).

The level loop is DELTA-STEPPING's light/heavy bucket loop (Meyer &
Sanders via Buluç & Madduri, arXiv:1104.4518): distances settle in
buckets of width ``delta`` — within the current bucket, only LIGHT edges
(weight <= delta) relax, repeated to a fixed point (a light relaxation
can keep landing inside the bucket); when the bucket stabilizes, one
relaxation over ALL edges (the heavy close — a heavy edge always lands
in a later bucket, so once per bucket suffices) and the bucket bound
advances by delta. Termination: nothing changed AND no finite tentative
distance sits at or above the bound — at that point every finite row has
relaxed out through every edge, a fixed point of Bellman-Ford, which is
exactly the SSSP solution for positive weights.

Serve protocol: ``dispatch``/``fetch`` halves like every packed engine
(the loop is one fused jitted while; JAX dispatch is async), on-device
per-lane summaries (reached count + weighted eccentricity — the
``levels`` a metadata-only query answers with), lazy per-lane distance
columns. Chaos sites ``sssp_dispatch``/``sssp_fetch`` mirror the packed
engines' dispatch/fetch sites (tpu_bfs/faults.py).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tpu_bfs import faults as _faults
from tpu_bfs.graph.csr import INF_DIST, Graph
from tpu_bfs.graph.ell import build_ell, build_ell_weights

#: On-device "unreached" tentative distance. 2**29 keeps every sum the
#: expansion forms (dist + weight, each <= INF_W) under 2**30, far from
#: int32 overflow, while any true shortest distance (< V * wmax) stays
#: far below it at every supported scale.
INF_W = np.int32(1 << 29)


def _check_kernel_ident():
    # The Pallas min-plus kernel bakes its identity as a symbolic
    # constant (ops/ell_expand.MINPLUS_IDENT); the two definitions must
    # agree or the kernel's gated/pad rows would not absorb under min.
    from tpu_bfs.ops.ell_expand import MINPLUS_IDENT

    assert MINPLUS_IDENT == int(INF_W), (MINPLUS_IDENT, int(INF_W))


def _make_min_plus_expand(spec_like, L: int, wsuf: str):
    """Min-plus bucketed-ELL expansion over a [rows, L] int32 distance
    table — make_fori_expand's shape with per-slot weight adds. ``wsuf``
    picks the weight plane: ``"w"`` (all edges — the heavy close) or
    ``"wl"`` (light-only: heavy slots hold INF_W, so their candidates
    are absorbed by the min)."""
    kcap = spec_like.kcap
    heavy = spec_like.num_virtual > 0
    num_virtual = spec_like.num_virtual
    fold_steps = spec_like.fold_steps
    light_meta = spec_like.light_meta
    tail_rows = spec_like.tail_rows

    def _full(shape):
        return jnp.full(shape, INF_W, jnp.int32)

    def expand(arrs, dist):
        parts = []
        if heavy:
            vr_t = arrs["virtual_t"]  # [kcap, M]
            vw = arrs["virtual_" + wsuf]  # [kcap, M]

            def vbody(kk, acc):
                return jnp.minimum(acc, dist[vr_t[kk]] + vw[kk][:, None])

            acc = jax.lax.fori_loop(
                0, kcap, vbody, _full((num_virtual, L))
            )
            vr_ext = jnp.concatenate([acc, _full((1, L))])
            cur = vr_ext[arrs["fold_pad_map"]]
            pyramid = [cur]
            for _ in range(fold_steps):
                pairs = cur.reshape(-1, 2, L)
                cur = jnp.minimum(pairs[:, 0], pairs[:, 1])
                pyramid.append(cur)
            pyr = jnp.concatenate(pyramid) if len(pyramid) > 1 else pyramid[0]
            parts.append(pyr[arrs["heavy_pick"]])
        for i, (k, n) in enumerate(light_meta):
            bt = arrs[f"light{i}_t"]  # [k, n]
            bw = arrs[f"light{i}_{wsuf}"]  # [k, n]

            def lbody(kk, acc, bt=bt, bw=bw):
                return jnp.minimum(acc, dist[bt[kk]] + bw[kk][:, None])

            parts.append(jax.lax.fori_loop(0, k, lbody, _full((n, L))))
        if tail_rows:
            parts.append(_full((tail_rows, L)))
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    return expand


class _Spec:
    """Shape metadata of the expansion (ExpandSpec's fields, local so the
    module stays importable without the packed machinery)."""

    def __init__(self, ell):
        self.kcap = ell.kcap
        self.heavy = ell.num_virtual > 0
        self.num_virtual = ell.num_virtual
        self.fold_steps = ell.fold_steps
        self.light_meta = tuple((b.k, b.n) for b in ell.light)
        self.tail_rows = ell.num_active - ell.num_nonzero + 1


class SsspDispatch:
    """An in-flight SSSP batch (async device references; fetch blocks)."""

    __slots__ = ("sources", "dist", "rounds", "alive", "t0")

    def __init__(self, sources, dist, rounds, alive, t0):
        self.sources = sources
        self.dist = dist
        self.rounds = rounds
        self.alive = alive
        self.t0 = t0


class SsspBatchResult:
    """Batch result with lazy per-lane distance columns.

    ``reached``/``ecc`` reduce on device ([L] each — one small transfer);
    ``distances_int32(i)`` pulls ONE [rows] column, maps it to real
    vertex ids, and caches it — the PackedBatchResult discipline, minus
    the bit slicing (SSSP distances are already int32 words)."""

    def __init__(self, engine, sources, dist, rounds, reached, ecc, iso,
                 elapsed_s=None):
        self._engine = engine
        self.sources = np.asarray(sources, dtype=np.int32)
        self._dist = dist  # device [rows, L] int32
        self.rounds = rounds  # delta-stepping bodies run
        n = len(self.sources)
        self.reached = np.asarray(reached)[:n].astype(np.int64)
        self.ecc = np.asarray(ecc)[:n].astype(np.int32)
        self.edges_traversed = None
        self.elapsed_s = elapsed_s
        self._iso = iso
        if iso is not None and iso.any():
            self.reached[iso] = 1
            self.ecc[iso] = 0
        self._col_cache: dict = {}

    @property
    def num_levels(self) -> int:
        """Max weighted distance over the batch (the BFS result's field
        name, kept so generic consumers read one protocol)."""
        return int(self.ecc.max()) if len(self.ecc) else 0

    def extras(self, i: int) -> dict:
        return {"weighted": True, "sssp_rounds": int(self.rounds)}

    def distances_int32(self, i: int) -> np.ndarray:
        if not (0 <= i < len(self.sources)):
            raise IndexError(i)
        eng = self._engine
        if self._iso is not None and self._iso[i]:
            d = np.full(eng.num_vertices, INF_DIST, np.int32)
            d[self.sources[i]] = 0
            return d
        if i not in self._col_cache:
            col = np.asarray(
                jax.lax.dynamic_slice_in_dim(self._dist, i, 1, axis=1)
            )[: eng._act, 0]
            full = np.full(eng.num_vertices, INF_DIST, np.int32)
            m = eng._rank < eng._act
            vals = col[eng._rank[m]]
            full[m] = np.where(vals >= INF_W, INF_DIST, vals)
            self._col_cache[i] = full
        return self._col_cache[i]


class SsspEngine:
    """Delta-stepping SSSP over the weighted bucketed ELL.

    ``lanes`` concurrent sources per batch (each an int32 column — keep
    it far below the BFS engines' bit-packed widths); ``delta`` is the
    bucket width (0 = auto: the mean edge weight, delta-stepping's usual
    operating point); ``max_rounds`` bounds the fused loop (a round is
    one light sweep or one heavy close — generously above any real
    bucket count; exceeding it raises rather than mislabeling)."""

    kind = "sssp"

    def __init__(self, graph: Graph, *, lanes: int = 32, kcap: int = 64,
                 delta: int = 0, max_rounds: int = 4096,
                 expand_impl: str = "xla", interpret: bool | None = None,
                 overlay: tuple = ()):
        from tpu_bfs.algorithms._packed_common import validate_expand_impl

        validate_expand_impl(expand_impl)
        self.overlay = tuple(int(x) for x in overlay) if overlay else ()
        self.expand_impl = expand_impl
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self._interpret = bool(interpret)
        if graph.weights is None:
            raise ValueError(
                "sssp needs a weighted graph (generate with weights=W or "
                "attach a weights plane)"
            )
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        self.host_graph = graph
        self.ell = build_ell(graph, kcap=kcap)
        self.lanes = int(lanes)
        self.num_vertices = graph.num_vertices
        self.undirected = graph.undirected
        self.max_rounds = int(max_rounds)
        self._act = self.ell.num_active
        self._rank = self.ell.rank
        self._table_rows = self._act + 1  # + the all-INF sentinel row
        wmax = int(graph.weights.max()) if len(graph.weights) else 1
        self.wmax = wmax
        if delta <= 0:
            delta = max(1, int(round(float(graph.weights.mean())))) \
                if len(graph.weights) else 1
        self.delta = int(delta)
        # The weighted eccentricity cap: rounds bound the loop, but the
        # distances themselves are only bounded by the graph.
        spec = _Spec(self.ell)
        self.arrs = self._build_arrays()
        if self.overlay:
            # Arm the fold's pytree keys with all-pad tables at build
            # so a later mutation swaps values without a retrace.
            from tpu_bfs.graph.dynamic import empty_overlay_tables

            self.set_overlay(empty_overlay_tables(
                self.overlay, self._act, weighted=True
            ))
        if expand_impl == "pallas":
            from tpu_bfs.algorithms._packed_common import make_pallas_expand
            from tpu_bfs.ops.ell_expand import validate_kernel_width

            _check_kernel_ident()
            # The min-plus kernel DMAs [1, L] distance rows: L is the
            # kernel width, so real TPUs need L % 128 (int32 lanes are
            # 32x wider than a BFS lane — 128 is a deliberately big
            # batch here, hence interpret-first until chip-measured).
            validate_kernel_width(
                self.lanes, self._interpret,
                kernel="sssp expand_impl='pallas'",
            )
            expand_light = make_pallas_expand(
                spec, self.lanes, op="minplus", wsuf="wl",
                interpret=self._interpret,
            )
            expand_full = make_pallas_expand(
                spec, self.lanes, op="minplus", wsuf="w",
                interpret=self._interpret,
            )
        else:
            expand_light = _make_min_plus_expand(spec, self.lanes, "wl")
            expand_full = _make_min_plus_expand(spec, self.lanes, "w")
        if self.overlay:
            # Dynamic-graph delta overlay (ISSUE 19): fold the mutation
            # tables' min-plus contributions over both expansion halves
            # — the light sweep reads the delta-thresholded ov_wl plane
            # (derived in set_overlay from ov_w and THIS engine's
            # delta), the heavy close the full ov_w plane, mirroring the
            # base tables' wl/w split exactly.
            from tpu_bfs.graph.dynamic import make_overlay_fold

            expand_light = make_overlay_fold(
                expand_light, op="minplus", weights_key="ov_wl"
            )
            expand_full = make_overlay_fold(
                expand_full, op="minplus", weights_key="ov_w"
            )
        self._core = _make_delta_core(
            expand_light, expand_full, jnp.int32(self.delta)
        )
        self._seed = _make_seed(self._table_rows, self.lanes)
        self._summaries = _make_summaries(self._act)
        self._warmed = False

    def _build_arrays(self) -> dict:
        from tpu_bfs.algorithms._packed_common import expand_arrays

        pallas = self.expand_impl == "pallas"
        if pallas:
            from tpu_bfs.algorithms._packed_common import (
                pallas_expand_arrays,
            )
            from tpu_bfs.graph.ell import pad_gate_blocks

        arrs = expand_arrays(self.ell)
        if pallas:
            # Whole-block index tables the kernel DMAs (sentinel = the
            # all-INF row act) ...
            for name, tbl in pallas_expand_arrays(
                self.ell, self._act
            ).items():
                arrs[name] = jnp.asarray(tbl)
        vw, lw = build_ell_weights(self.host_graph, self.ell, pad=0)
        delta = self.delta

        def _weight_planes(prefix, wt):
            arrs[f"{prefix}_w"] = jnp.asarray(wt)
            # Light plane: heavy-edge slots absorb under min. Pad slots
            # (weight 0) gather the all-INF sentinel row either way.
            wl = np.where(wt <= delta, wt, INF_W)
            arrs[f"{prefix}_wl"] = jnp.asarray(wl)
            if pallas:
                # ... and the weight planes padded slot-for-slot with
                # them (pad weight 0: the padded index slot gathers the
                # INF sentinel row, INF + 0 = the min identity).
                arrs[f"{prefix}_w_gt"] = jnp.asarray(
                    pad_gate_blocks(wt, 0)
                )
                arrs[f"{prefix}_wl_gt"] = jnp.asarray(
                    pad_gate_blocks(wl, 0)
                )

        if vw is not None:
            _weight_planes(
                "virtual", np.ascontiguousarray(vw.T).astype(np.int32)
            )
        for i, w in enumerate(lw):
            _weight_planes(
                f"light{i}", np.ascontiguousarray(w.T).astype(np.int32)
            )
        return arrs

    def set_overlay(self, tables) -> None:
        """Swap the delta-overlay tables under the compiled core
        (ISSUE 19). The light plane ``ov_wl`` is derived HERE from
        ``ov_w`` and this engine's ``delta`` — the bucket width is a
        per-engine tuning knob the graph layer cannot know — with pad
        slots (weight 0) passing the threshold and gathering the all-INF
        sentinel row, which absorbs under min. One atomic dict rebind;
        shapes must match the armed capacity (fixed compiled pytree)."""
        if not self.overlay:
            raise ValueError(
                "engine built without an overlay — pass overlay=(rows, "
                "kcap) at construction to serve a dynamic graph"
            )
        rows, kcap = self.overlay
        new = {}
        for name in ("ov_rows", "ov_idx", "ov_override", "ov_w"):
            arr = np.asarray(tables[name], np.int32)
            want = (rows, kcap) if name in ("ov_idx", "ov_w") else (rows,)
            if arr.shape != want:
                raise ValueError(
                    f"{name} shape {arr.shape} != armed capacity {want}"
                )
            new[name] = arr
        wl = np.where(new["ov_w"] <= self.delta, new["ov_w"], INF_W)
        dev = {k: jnp.asarray(v) for k, v in new.items()}
        dev["ov_wl"] = jnp.asarray(wl.astype(np.int32))
        self.arrs = {**self.arrs, **dev}

    def _iso_of(self, sources: np.ndarray):
        return self._rank[sources] >= self._act

    def dispatch(self, sources, **_ignored) -> SsspDispatch:
        if _faults.ACTIVE is not None:
            # Chaos-harness injection site (tpu_bfs/faults.py): the
            # workload twin of the packed engines' "dispatch" site.
            _faults.ACTIVE.hit("sssp_dispatch", lanes=self.lanes)
        sources = np.asarray(sources, dtype=np.int64)
        if sources.ndim != 1 or not (1 <= len(sources) <= self.lanes):
            raise ValueError(
                f"need 1..{self.lanes} sources, got {sources.shape}"
            )
        if sources.min() < 0 or sources.max() >= self.num_vertices:
            raise ValueError("source out of range")
        rows = self._rank[sources].astype(np.int64)
        keep = rows < self._act
        lanes_idx = np.arange(len(sources), dtype=np.int32)
        dist0 = self._seed(
            jnp.asarray(np.where(keep, rows, 0).astype(np.int32)),
            jnp.asarray(lanes_idx),
            jnp.asarray(keep),
        )
        t0 = time.perf_counter()
        dist, rounds, alive = self._core(
            self.arrs, dist0, jnp.int32(self.max_rounds)
        )
        return SsspDispatch(sources, dist, rounds, alive, t0)

    def fetch(self, pend: SsspDispatch, *, check_cap: bool = True,
              time_it: bool = False) -> SsspBatchResult:
        if _faults.ACTIVE is not None:
            # Chaos site: the blocking result half (slow/transient/oom
            # kinds here surface exactly like a real async failure).
            _faults.ACTIVE.hit("sssp_fetch", lanes=self.lanes)
        rounds = int(pend.rounds)  # blocks until the loop finishes
        elapsed = (time.perf_counter() - pend.t0) if time_it else None
        self._warmed = True
        if check_cap and bool(pend.alive):
            raise RuntimeError(
                f"sssp still relaxing after {rounds} rounds "
                f"(max_rounds={self.max_rounds}) — raise max_rounds or "
                f"delta for this graph"
            )
        reached, ecc = self._summaries(pend.dist)
        iso = self._iso_of(pend.sources)
        return SsspBatchResult(
            self, pend.sources, pend.dist, rounds, reached, ecc,
            iso if iso.any() else None, elapsed_s=elapsed,
        )

    def run(self, sources, *, time_it: bool = False, check_cap: bool = True,
            **_ignored) -> SsspBatchResult:
        if time_it and not self._warmed:
            int(self.dispatch(sources).rounds)
        return self.fetch(
            self.dispatch(sources), check_cap=check_cap, time_it=time_it
        )

    def analysis_programs(self):
        """Static-analyzer hook (tpu_bfs/analysis): the delta-stepping
        core over an example seeded table — the dtype walk proves the
        loop stays 32-bit, the memory pass prices it, and the donation
        certificate pins the donated carry's HLO alias."""
        dist0 = self._seed(
            jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32),
            jnp.ones((1,), bool),
        )
        return [
            ("sssp_core", self._core, (self.arrs, dist0, jnp.int32(64))),
            ("sssp_summaries", self._summaries, (dist0,)),
        ]


def _make_seed(rows: int, L: int):
    @jax.jit
    def seed(rws, cols, keep):
        # Isolated sources (no table row) scatter INF at row 0 — a no-op
        # under min; their lanes patch host-side (SsspBatchResult._iso).
        dist0 = jnp.full((rows, L), INF_W, jnp.int32)
        vals = jnp.where(keep, jnp.int32(0), INF_W)
        return dist0.at[rws, cols].min(vals)

    return seed


def _make_delta_core(expand_light, expand_full, delta):
    @partial(jax.jit, donate_argnums=(1,))
    def core(arrs, dist0, max_rounds):
        def cond(carry):
            _, _, alive, rounds = carry
            return alive & (rounds < max_rounds)

        def body(carry):
            dist, hi, _, rounds = carry
            # Current bucket + settled rows relax out; later buckets are
            # masked to INF (their candidates could only be improved by
            # the bucket rows anyway — the delta-stepping invariant).
            masked = jnp.where(dist < hi, dist, INF_W)
            new = jnp.minimum(dist, expand_light(arrs, masked))
            changed_l = jnp.any(new < dist)

            def close(d):
                # Bucket stabilized: one relaxation over ALL edges (the
                # heavy close) before the bound advances.
                m = jnp.where(d < hi, d, INF_W)
                return jnp.minimum(d, expand_full(arrs, m))

            new2 = jax.lax.cond(changed_l, lambda d: d, close, new)
            changed = changed_l | jnp.any(new2 < new)
            hi2 = jnp.where(changed_l, hi, hi + delta)
            # Finite distances at/above the bound still need bucketing;
            # with none left and nothing changed, every finite row has
            # relaxed through every edge — the Bellman-Ford fixed point.
            unsettled = jnp.any((new2 < INF_W) & (new2 >= hi2))
            return new2, hi2, changed | unsettled, rounds + 1

        dist, _, alive, rounds = jax.lax.while_loop(
            cond, body, (dist0, delta, jnp.bool_(True), jnp.int32(0))
        )
        return dist, rounds, alive

    # The ISSUE 13 donation tag: the seeded table is dead after the call
    # (every dispatch seeds afresh), so the loop's output aliases its
    # buffer; the analyzer's HLO certificate pins the alias landed.
    core._donate_argnums = (1,)
    return core


def _make_summaries(act: int):
    @jax.jit
    def summaries(dist):
        if act == 0:
            # Edgeless tables: every lane's component is its source.
            L = dist.shape[1]
            return jnp.zeros((L,), jnp.int32), jnp.zeros((L,), jnp.int32)
        d = dist[:act]
        fin = d < INF_W
        reached = jnp.sum(fin.astype(jnp.int32), axis=0)
        ecc = jnp.max(jnp.where(fin, d, 0), axis=0)
        return reached, ecc

    return summaries
